"""The overload-tolerant query scheduler (ISSUE 8 tentpole).

One ``Scheduler`` owns N dispatch-slot threads
(``SRJT_SERVE_MAX_CONCURRENT``). ``submit()`` enqueues a query into
its tenant's bounded FIFO queue; slots pull queries via STRIDE
scheduling (weighted-fair: each tenant carries a ``pass`` value
advanced by ``stride = K / weight`` per dispatch, and the non-empty
tenant with the minimum pass runs next — a saturating tenant advances
its pass N× faster than a trickling one, so the trickle keeps its
share). Admission is where ALL load shedding happens:

    submit() ──▶ QUEUED ──(weighted-fair dispatch)──▶ RUNNING ──▶ done
       │shed          │cancel()/expire                │cancel() ─▶ token
       ▼              ▼                               ▼
    Overloaded     cancelled/expired             cancelled/failed
    (retryable,    (DeadlineExceeded)            (DeadlineExceeded)
     retry_after_s)

Shed decisions (every one a retryable ``Overloaded`` raised to the
SUBMITTER, or completed into an evicted victim's handle — never a
mid-flight kill, never a timeout in disguise):

- **queue_full**: the tenant's queue is at ``SRJT_SERVE_QUEUE_DEPTH``.
  Lowest-priority-first: an incoming query of strictly higher priority
  evicts the queue's lowest-priority entry instead of being refused.
- **pressure**: the overload controller trips — global queued count at
  ``SRJT_SERVE_MAX_QUEUED``, the oldest queued query older than
  ``SRJT_SERVE_MAX_QUEUE_AGE_SEC``, or the memory governor reporting
  blocked admissions — and the incoming query does not outrank the
  lowest-priority queued one.
- **doa_deadline**: the submission's effective budget is already gone
  at admission (fast-fail beats queuing work that must expire).
- **breaker**: the sidecar pool is dark (circuit breaker OPEN) and the
  query declared ``host_eligible=False`` — host-engine-eligible work
  keeps flowing when the pool is down.
- **quarantine** (ISSUE 9): every LIVE pool worker is quarantined by
  the gray-failure detector (sidecar_pool.py) and the query declared
  ``host_eligible=False`` — device-only work is shed instead of
  queueing onto known stragglers; host-eligible work keeps flowing.
- **cluster_degraded** (ISSUE 16): an attached
  ``parallel.cluster.ClusterView`` is below quorum — too many exchange
  ranks DEAD for a distributed query to complete; refused at admission
  (retryable: quorum returns when replacement ranks join) instead of
  queued into a fabric that would burn retry budgets mid-exchange.
- **shutting_down**: ``shutdown()`` was called.
- **injected**: the fault injector's ``reject`` kind fired at the
  ``serve.admit`` choke point (deterministic shed-path chaos).

Deadlines span the QUEUE: a query's budget starts at submit, so one
that expires while queued never dispatches (``serve.expired_in_queue``)
and a dispatched one runs under ``deadline.scope`` with whatever
budget the wait left — cancel() trips the handle's CancelToken, which
the PR 3 machinery propagates through retry backoffs, shuffle
escalations, and sidecar socket deadlines.

Observability: durable counters are registry-direct
(``serve.submitted/completed/failed/cancelled``, ``serve.shed_total``
+ ``serve.shed.<cause>``, ``serve.expired_in_queue``, queue/running
gauges); queue-wait/run/e2e histograms and ``serve.*`` events ride the
``SRJT_METRICS_ENABLED`` gate like every other hot path.
"""

from __future__ import annotations

import itertools
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..utils import deadline as deadline_mod
from ..utils import faultinj, knobs, metrics, tracing
from ..utils.deadline import CancelToken
from ..utils.errors import DeadlineExceeded, Overloaded

__all__ = [
    "Scheduler",
    "QueryHandle",
    "SHED_CAUSES",
    "scheduler",
    "submit",
    "shutdown_scheduler",
    "stats_section",
    "live_scheduler_count",
    "leak_report",
]

# handle states
S_QUEUED = "queued"
S_RUNNING = "running"
S_DONE = "done"
S_FAILED = "failed"
S_CANCELLED = "cancelled"
S_SHED = "shed"
S_EXPIRED = "expired"

_FINAL = (S_DONE, S_FAILED, S_CANCELLED, S_SHED, S_EXPIRED)

# handle state -> srjt-trace root status (the flight recorder flushes
# every non-"ok" trace, so shed/failed/expired/cancelled queries from a
# storm are all captured with their span trees)
_TRACE_STATUS = {
    S_DONE: "ok",
    S_FAILED: "failed",
    S_CANCELLED: "cancelled",
    S_SHED: "shed",
    S_EXPIRED: "expired",
}


def _shed_trace(qt, cause: str) -> None:
    """Finish a (possibly None) root trace as shed — the recorder's
    capture of an admission-rejected query."""
    if qt is not None:
        qt.annotate(shed_cause=cause)
        qt.finish("shed")

SHED_CAUSES = ("queue_full", "pressure", "doa_deadline", "breaker",
               "quarantine", "cluster_degraded", "shutting_down",
               "injected", "forecast")

# stride scheduling: pass advance per dispatch for weight 1.0
_STRIDE1 = float(1 << 20)

# lane-map size at which creating a NEW tenant first prunes idle lanes
_LANE_PRUNE_AT = 64


class QueryHandle:
    """The submitter's view of one query: ``result()`` / ``cancel()`` /
    ``status()``. Created only by ``Scheduler.submit``."""

    __slots__ = (
        "_scheduler", "_fn", "_args", "_kwargs", "tenant", "priority",
        "query_id", "_memory_bytes", "host_eligible", "_token", "_done",
        "_state", "_result", "_exc", "_t_submit", "_t_deadline",
        "_t_dispatch", "_budget_s", "_trace", "_predicted_cost_s",
        "_jid",
    )

    def __init__(self, scheduler, fn, args, kwargs, tenant, priority,
                 budget_s, memory_bytes, host_eligible, query_id,
                 t_submit):
        self._scheduler = scheduler
        self._fn = fn
        self._args = args
        self._kwargs = kwargs
        self.tenant = tenant
        self.priority = int(priority)
        self.query_id = query_id
        self._memory_bytes = memory_bytes
        self.host_eligible = bool(host_eligible)
        self._token = CancelToken()
        self._done = threading.Event()
        self._state = S_QUEUED
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self._t_submit = t_submit
        self._budget_s = budget_s
        self._t_deadline = None if budget_s is None else t_submit + budget_s
        self._t_dispatch: Optional[float] = None
        self._trace = None  # srjt-trace root (tracing.QueryTrace), or None
        # observed-cost EWMA of the cached plan structure (srjt-cache),
        # None for uncached/never-run plans — the forecast controller's
        # per-query input
        self._predicted_cost_s: Optional[float] = None
        # durable-journal id (srjt-durable, ISSUE 20): set under the
        # admission lock when the journal is armed, None otherwise —
        # the one-attribute-read gate every state-transition write pays
        self._jid: Optional[str] = None

    # -- the public surface --------------------------------------------------

    def status(self) -> str:
        """One of queued/running/done/failed/cancelled/shed/expired."""
        return self._state

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout_s: Optional[float] = None):
        """Block for the outcome: the fn's return value, or re-raise
        its failure (``Overloaded`` for a shed, ``DeadlineExceeded``
        for expiry/cancellation, the fn's own exception otherwise).
        ``timeout_s`` bounds the WAIT, not the query — on timeout the
        query keeps running and a ``TimeoutError`` is raised here."""
        if not self._done.wait(timeout_s):
            raise TimeoutError(
                f"query {self.query_id} not done after {timeout_s}s "
                f"(state={self._state})"
            )
        if self._exc is not None:
            raise self._exc
        return self._result

    def cancel(self, reason: str = "cancelled") -> bool:
        """Cancel cooperatively: a QUEUED query completes immediately
        (``DeadlineExceeded``, never dispatched); a RUNNING one has its
        CancelToken tripped — the PR 3 machinery unwinds it at the next
        cancel point (op boundary, retry backoff, sidecar socket
        deadline) with no sidecar desync, because the token rides the
        SAME deadline scope every layer already consults. False when
        the query already reached a final state."""
        return self._scheduler._cancel(self, reason)

    def exception(self) -> Optional[BaseException]:
        """The stored failure after completion (None while pending or
        on success) — for callers polling instead of result()."""
        return self._exc

    def __repr__(self):
        return (f"QueryHandle(id={self.query_id}, tenant={self.tenant!r}, "
                f"priority={self.priority}, state={self._state})")


class _Tenant:
    """Per-tenant QoS state: the bounded FIFO queue + stride lane.
    INVARIANT: the deque holds only S_QUEUED handles — every finish
    path (cancel/evict/shutdown) removes under the scheduler lock and
    the dispatcher pops — so ``len(q)`` IS the queue depth and ``q[0]``
    the tenant's oldest queued query."""

    __slots__ = ("name", "q", "weight", "stride", "pass_", "submitted",
                 "completed", "failed", "shed", "expired", "cancelled")

    def __init__(self, name: str, weight: float):
        self.name = name
        self.q: deque = deque()
        self.weight = 1.0
        self.stride = _STRIDE1
        self.set_weight(weight)
        self.pass_ = 0.0
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.shed = 0
        self.expired = 0
        self.cancelled = 0

    def set_weight(self, weight: float) -> None:
        w = float(weight)
        if w <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        self.weight = w
        self.stride = _STRIDE1 / w


class Scheduler:
    """The concurrent serving runtime: see the module docstring for
    the state machine and shed taxonomy. One instance owns its worker
    threads; ``shutdown()`` joins them all (the leak assertion in
    tests/conftest.py holds every session to that)."""

    def __init__(
        self,
        max_concurrent: Optional[int] = None,
        queue_depth: Optional[int] = None,
        max_queued: Optional[int] = None,
        max_queue_age_s: Optional[float] = None,
        retry_after_s: Optional[float] = None,
        name: str = "serve",
        clock=time.monotonic,
    ):
        self.name = str(name)
        self._clock = clock
        self._slots = int(
            knobs.get_int("SRJT_SERVE_MAX_CONCURRENT")
            if max_concurrent is None else max_concurrent
        )
        if self._slots < 1:
            raise ValueError(f"max_concurrent must be >= 1, got {self._slots}")
        self._queue_depth = int(
            knobs.get_int("SRJT_SERVE_QUEUE_DEPTH")
            if queue_depth is None else queue_depth
        )
        if self._queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {self._queue_depth}")
        self._max_queued = int(
            knobs.get_int("SRJT_SERVE_MAX_QUEUED")
            if max_queued is None else max_queued
        )
        self._max_queue_age_s = float(
            knobs.get_float("SRJT_SERVE_MAX_QUEUE_AGE_SEC")
            if max_queue_age_s is None else max_queue_age_s
        )
        self._retry_after_s = float(
            knobs.get_float("SRJT_SERVE_RETRY_AFTER_SEC")
            if retry_after_s is None else retry_after_s
        )
        self._cond = threading.Condition(threading.Lock())
        # srjt-race layer 2: the tenant-lane table is tracked — every
        # key/iteration access is checked for happens-before ordering
        # when SRJT_RACE=1 (a plain dict otherwise, zero cost)
        from ..analysis.lockdep import track as _race_track

        self._tenants: Dict[str, _Tenant] = _race_track(
            {}, f"serve.{self.name}.tenants"
        )
        self._queued = 0  # entries in S_QUEUED across all tenant deques
        self._running = 0
        self._cluster = None  # ClusterView (ISSUE 16): quorum-loss shed
        self._inflight: set = set()
        self._pass_floor = 0.0
        self._open = True
        self._ids = itertools.count(1)
        self._reg().gauge("serve.slots").set(self._slots)
        self._workers = [
            threading.Thread(
                target=self._worker_loop,
                name=f"srjt-serve-{self.name}-{i}",
                daemon=True,
            )
            for i in range(self._slots)
        ]
        with _live_lock:
            _LIVE.add(self)
            global _ever_created
            _ever_created = True
        for w in self._workers:
            w.start()

    # -- plumbing ------------------------------------------------------------

    @staticmethod
    def _reg():
        return metrics.registry()

    def _count_shed(self, cause: str) -> None:
        """Durable shed accounting (registry-direct, the breaker
        contract): chaos gates assert serve.shed_total > 0 from these.
        Counters only — safe under the dispatch lock; the matching
        ``serve.shed`` EVENT (file I/O) is emitted by ``_shed_event``
        strictly outside it. Per-tenant ``t.shed`` is bumped only on
        the in-lock paths (queue_full/pressure/eviction/shutdown): the
        pre-admission sheds (doa/breaker/injected) deliberately create
        no lane for a tenant the scheduler never admitted, so they
        count in the registry totals only."""
        reg = self._reg()
        reg.counter("serve.shed_total").inc()
        reg.counter(f"serve.shed.{cause}").inc()
        # shed-pressure stamp (ISSUE 9): the sidecar pool's hedged
        # dispatch auto-disarms within SRJT_HEDGE_SHED_WINDOW_S of this
        # monotonic timestamp — an overloaded pool must not carry
        # duplicate load on top of the traffic it is already shedding
        reg.gauge("serve.last_shed_s").set(time.monotonic())

    @staticmethod
    def _shed_event(tenant: str, cause: str) -> None:
        metrics.event("serve.shed", tenant=tenant, cause=cause)

    def _overloaded(self, msg: str, cause: str,
                    retry_after_s: Optional[float] = None) -> Overloaded:
        return Overloaded(
            f"{self.name}: {msg}",
            retry_after_s=self._retry_after_s if retry_after_s is None
            else retry_after_s,
            cause=cause,
        )

    def _tenant_locked(self, name: str, weight) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            if len(self._tenants) >= _LANE_PRUNE_AT:
                # high-cardinality tenant churn (per-user/session ids):
                # drop idle lanes (empty queue) so every dispatch scan
                # stays O(active tenants) and dead lane objects cannot
                # accumulate. A pruned tenant's counters live on in the
                # registry totals; a returning one re-enters at the
                # pass floor — no fairness credit lost or gained.
                for idle in [n for n, tt in self._tenants.items()
                             if not tt.q]:
                    del self._tenants[idle]
            t = _Tenant(name, 1.0 if weight is None else weight)
            # a new lane starts at the pass floor so it cannot claim
            # credit for the time it did not exist
            t.pass_ = self._pass_floor
            self._tenants[name] = t
            self._reg().gauge("serve.tenants").set(len(self._tenants))
        elif weight is not None:
            t.set_weight(weight)
        if not t.q:
            # idle -> busy: forfeit accumulated lag (stride discipline —
            # an hour-idle tenant must not monopolize the next hour)
            t.pass_ = max(t.pass_, self._pass_floor)
        return t

    def set_tenant_weight(self, tenant: str, weight: float) -> None:
        """Re-weight a tenant's fair share (stride = K / weight)."""
        with self._cond:
            self._tenant_locked(str(tenant), weight)

    def attach_cluster(self, cluster) -> None:
        """Attach a ``parallel.cluster.ClusterView``: while the cluster
        is below quorum (``has_quorum()`` false), every submit sheds
        retryable ``Overloaded(cause="cluster_degraded")`` — a cluster
        that cannot answer distributed queries correctly must refuse
        them upfront, not let them queue and fail mid-exchange. Pass
        None to detach."""
        with self._cond:
            self._cluster = cluster

    # -- admission (submit + the overload controller) ------------------------

    def submit(
        self,
        fn: Callable,
        *args,
        tenant: str = "default",
        deadline_s: Optional[float] = None,
        priority: int = 0,
        memory_bytes: Optional[int] = None,
        host_eligible: bool = True,
        weight: Optional[float] = None,
        idempotency_key: Optional[str] = None,
        recovered: bool = False,
        **kwargs,
    ) -> QueryHandle:
        """Admit one query (a callable or a CompiledPipeline — anything
        callable) for concurrent execution. Raises retryable
        ``Overloaded`` instead of queueing when admission must shed;
        see the module docstring for the cause taxonomy. ``deadline_s``
        starts at SUBMIT (queue wait spends it); an ambient deadline
        scope at the call site clamps it further and a dead one is
        rejected on arrival. ``memory_bytes`` pre-admits the whole
        query's footprint with the memory governor when it is armed
        (inner op boundaries then skip their own admission, the
        standard nesting discipline).

        srjt-durable (ISSUE 20): with ``SRJT_JOURNAL_DIR`` armed, a
        client-supplied ``idempotency_key`` whose journaled twin
        already reached DONE returns a pre-completed handle carrying a
        ``journal.DigestAnswer`` (no re-execution); otherwise the
        admitted query's submit record is fsync'd to the journal before
        this method returns. ``recovered=True`` marks a recovery
        resubmission — the trace ring annotates the restart seam."""
        plan_node = None
        if not callable(fn):
            # srjt-plan (ISSUE 14): a logical-plan node is submittable
            # directly, with the bound tables dict as the sole
            # positional argument. Only TYPE-validated here — the
            # compile itself (rewrite fixpoint + host domain scans)
            # runs after the cheap pre-admission shed checks below, so
            # a breaker/injected/dead-budget shed never pays it.
            from ..plan import Node as _PlanNode

            if isinstance(fn, _PlanNode):
                if len(args) != 1 or not isinstance(args[0], dict):
                    raise TypeError(
                        "submitting a logical plan requires the bound "
                        "tables dict as the only positional argument"
                    )
                plan_node = fn
            else:
                raise TypeError(
                    f"submit() needs a callable, a compiled pipeline, or "
                    f"a logical plan, got {type(fn).__name__}"
                )
        tenant = str(tenant)
        # srjt-trace (ISSUE 12): the root trace opens AT SUBMIT so the
        # queue wait is inside the query's span tree, and so every shed
        # — even a pre-admission one — reaches the flight recorder with
        # its cause. One boolean read (None back) when tracing is off.
        qt = tracing.start_trace(
            "serve.query", tenant=tenant, priority=int(priority)
        )
        # srjt-durable (ISSUE 20): one env read when the journal is off
        # — the whole cost of the volatile posture
        from . import journal as journal_mod

        jrn = journal_mod.active()
        if recovered and qt is not None:
            # the restart seam: explain_last() on a resumed query shows
            # where the pre-crash lifetime ended and this one began
            qt.annotate(recovery=True)
        if idempotency_key is not None and jrn is not None:
            hit = jrn.done_digest(idempotency_key)
            if hit is not None:
                # the original completed before the crash: answer by
                # journaled digest, never re-execute DONE work
                jid, digest = hit
                self._reg().counter("journal.idempotent_hits").inc()
                with self._cond:
                    qid = next(self._ids)
                q = QueryHandle(self, None, (), {}, tenant, priority,
                                None, None, host_eligible, qid,
                                self._clock())
                q._state = S_DONE
                q._result = journal_mod.DigestAnswer(
                    idempotency_key, digest, jid
                )
                q._done.set()
                metrics.event(
                    "serve.idempotent_hit", query=qid, tenant=tenant,
                    idem=idempotency_key, jid=jid,
                )
                if qt is not None:
                    qt.annotate(idempotent_hit=True, jid=jid)
                    qt.finish("ok")
                return q
        # deterministic shed chaos: the `reject` kind keyed serve.admit
        try:
            faultinj.maybe_inject("serve.admit")
        except Overloaded:
            self._count_shed("injected")
            self._shed_event(tenant, "injected")
            _shed_trace(qt, "injected")
            raise
        # breaker- AND quarantine-aware routing (ISSUE 9): a dark pool
        # sheds only the work that CANNOT run on the host engine, and a
        # pool whose every live worker is QUARANTINED (gray, not dead —
        # the breaker never sees it) sheds the same way: queueing
        # device-only work onto known stragglers just converts sheds
        # into deadline expiries
        if not host_eligible:
            from .. import sidecar, sidecar_pool

            if sidecar.breaker().state() != "closed":
                self._count_shed("breaker")
                self._shed_event(tenant, "breaker")
                _shed_trace(qt, "breaker")
                raise self._overloaded(
                    "sidecar pool dark (breaker open) and query is not "
                    "host-engine-eligible", "breaker",
                )
            pool = sidecar_pool.current_pool()
            if (pool is not None and pool.live_count() > 0
                    and pool.routable_count() == 0):
                self._count_shed("quarantine")
                self._shed_event(tenant, "quarantine")
                _shed_trace(qt, "quarantine")
                raise self._overloaded(
                    "every live pool worker is quarantined (gray "
                    "failure) and query is not host-engine-eligible",
                    "quarantine",
                )
        # cluster-degraded shed (ISSUE 16): below quorum, a distributed
        # query cannot complete correctly — exchanges to dead ranks
        # would just burn retry budgets; refuse at admission instead,
        # retryable (quorum returns when replacements join)
        with self._cond:
            cluster = self._cluster
        if cluster is not None and not cluster.has_quorum():
            self._count_shed("cluster_degraded")
            self._shed_event(tenant, "cluster_degraded")
            _shed_trace(qt, "cluster_degraded")
            raise self._overloaded(
                f"cluster below quorum ({len(cluster.alive_ranks())}/"
                f"{cluster.world} ranks alive)", "cluster_degraded",
            )
        # dead-on-arrival deadline: fast-fail beats queueing work that
        # must expire (the effective budget inherits + clamps to an
        # ambient scope active at the submit site)
        outer = deadline_mod.current()
        eff = deadline_s if deadline_s is not None else deadline_mod.default_budget()
        if eff is not None:
            eff = float(eff)
        if outer is not None:
            rem = outer.remaining()
            if not math.isinf(rem):
                eff = rem if eff is None else min(eff, rem)
        if (eff is not None and eff <= 0) or (outer is not None and outer.done()):
            self._count_shed("doa_deadline")
            self._shed_event(tenant, "doa_deadline")
            _shed_trace(qt, "doa_deadline")
            raise self._overloaded(
                f"query dead on arrival (budget "
                f"{'cancelled' if outer is not None and outer.cancelled() else 'exhausted'} "
                "at submit)", "doa_deadline",
            )
        if plan_node is not None:
            # compile NOW, after the pre-admission sheds: the plan's
            # stage estimates must exist before queueing (memgov
            # pre-admission and the overload controller consume
            # memory_bytes), so the compile cannot move into the
            # dispatch slot — but the XLA compile itself is lazy
            # (first __call__), so the slot still pays that part
            from ..utils import knobs as _knobs

            if _knobs.get_bool("SRJT_PLAN_CACHE"):
                # srjt-cache: a parameterized-fingerprint hit skips
                # rewrite→verify→compile entirely and single-flights
                # identical concurrent submissions (the CachedQuery
                # wrapper also carries the structure's cost EWMA for
                # the forecast controller below)
                from .. import cache as _cache

                fn = _cache.compile_cached(
                    plan_node, args[0], name=f"serve.{tenant}"
                )
            else:
                from ..plan import compile_ir as _compile_ir

                fn = _compile_ir(plan_node, args[0], name=f"serve.{tenant}")
            args = ()
        if memory_bytes is None:
            # plan-derived pre-admission (ROADMAP item-2 follow-up):
            # compiled plans carry per-stage estimates — the scheduler's
            # memgov pre-admission and the overload controller see a
            # real footprint instead of a hand-fed number. An
            # out-of-core plan (srjt-ooc, ISSUE 18) admits its
            # PER-PARTITION peak: the whole-plan estimate exceeds the
            # budget by construction, and admitting it would reject the
            # very strategy chosen to fit — the downgrade is counted.
            ooc_peak = getattr(fn, "partition_memory_bytes", None)
            if ooc_peak is not None and ooc_peak > 0:
                memory_bytes = ooc_peak
                self._reg().counter("memgov.ooc_admissions").inc()
            else:
                memory_bytes = getattr(fn, "estimated_memory_bytes", None)
        if memory_bytes is not None and memory_bytes <= 0:
            # a zero/negative estimate is not "needs no memory", it is
            # "no usable estimate": 0 would sail through memgov
            # pre-admission as a free query and starve real admissions
            # of their accounting — normalize to None (un-estimated)
            # and count the bad input
            self._reg().counter("serve.bad_estimate").inc()
            memory_bytes = None
        predicted_cost_s = getattr(fn, "predicted_cost_s", None)
        shed_exc: Optional[Overloaded] = None
        victim: Optional[QueryHandle] = None
        victim_cause: Optional[str] = None
        with self._cond:
            if not self._open:
                self._count_shed("shutting_down")
                shed_exc = self._overloaded(
                    "scheduler shutting down", "shutting_down",
                )
            else:
                t = self._tenant_locked(tenant, weight)
                now = self._clock()
                q = QueryHandle(self, fn, args, kwargs, tenant, priority,
                                eff, memory_bytes, host_eligible,
                                next(self._ids), now)
                q._predicted_cost_s = predicted_cost_s
                # admission shedding, lowest-priority-first, at most
                # ONE eviction per admitted query. The per-tenant bound
                # is the harder constraint and is checked first: an
                # eviction there keeps the GLOBAL queued count flat
                # too, so the pressure cap stays honored without a
                # second victim.
                if len(t.q) >= self._queue_depth:
                    # bounded per-tenant FIFO — never unbounded buffering
                    victim = self._evict_locked(t, q, "queue_full")
                    if victim is None:
                        t.shed += 1
                        self._count_shed("queue_full")
                        shed_exc = self._overloaded(
                            f"tenant {tenant!r} queue full "
                            f"({self._queue_depth} deep)", "queue_full",
                        )
                    else:
                        victim_cause = "queue_full"
                else:
                    # overload controller: global depth / queue age /
                    # memgov pressure shed lowest-priority-first
                    cause = self._pressure_cause_locked(
                        now, incoming_cost=predicted_cost_s
                    )
                    if cause is not None:
                        victim = self._evict_locked(None, q, cause)
                        if victim is None:
                            t.shed += 1
                            self._count_shed(cause)
                            shed_exc = self._overloaded(
                                f"overloaded ({cause}): {self._queued} "
                                f"queued, priority {priority} does not "
                                "outrank the queue", cause,
                            )
                        else:
                            victim_cause = cause
                if shed_exc is None:
                    if qt is not None:
                        # attach the trace BEFORE the handle becomes
                        # visible to a dispatcher: the notify below can
                        # wake a slot that runs the query immediately,
                        # and a late-published _trace would leave the
                        # root unfinished (annotate is dict writes —
                        # in-lock-safe; trace I/O stays outside)
                        qt.annotate(query=q.query_id, budget_s=eff)
                        q._trace = qt
                    if jrn is not None:
                        # the jid is published BEFORE the handle becomes
                        # dispatchable (string assignment only — journal
                        # I/O stays outside the lock): a slot that runs
                        # the query immediately must see it, or its
                        # DISPATCHED record would be lost
                        q._jid = f"{os.getpid()}-{q.query_id}"
                    t.q.append(q)
                    t.submitted += 1
                    self._queued += 1
                    reg = self._reg()
                    reg.counter("serve.submitted").inc()
                    reg.gauge("serve.queued").set(self._queued)
                    self._cond.notify()
        # event I/O (one file write per line) strictly OUTSIDE the
        # dispatch lock — a shed storm must not serialize admission and
        # dispatch behind the event log; trace finishing (span-log
        # writes, the flight-recorder flush) follows the same rule
        if victim is not None:
            self._shed_event(victim.tenant, victim_cause)
            _shed_trace(victim._trace, victim_cause)
            # an admitted-then-evicted query's lifecycle closes in the
            # journal too (outside the lock, before its waiters wake)
            self._journal_state(victim, S_SHED, cause=victim_cause)
            victim._done.set()
        if shed_exc is not None:
            self._shed_event(tenant, shed_exc.cause)
            _shed_trace(qt, shed_exc.cause)
            raise shed_exc
        if q._jid is not None:
            # the durable submit record, fsync'd BEFORE the handle is
            # returned: a coordinator that dies after this point can
            # replay the query; one that dies before it never handed
            # out a handle. Submit-time sheds above never journal —
            # they were never admitted.
            rec: Dict[str, Any] = {
                "jid": q._jid, "tenant": tenant,
                "priority": int(priority), "deadline_s": eff,
                "memory_bytes": memory_bytes,
                "host_eligible": bool(host_eligible),
            }
            if idempotency_key is not None:
                rec["idem"] = idempotency_key
            if recovered:
                rec["recovered"] = True
            bindings = None
            if plan_node is not None:
                from ..plan.rewrites import parameterized_fingerprint

                pf = parameterized_fingerprint(plan_node)
                bindings = journal_mod.sanitize_bindings(pf.bindings)
                if bindings is not None:
                    rec["pf"] = pf.key
                    rec["bindings"] = bindings
            if bindings is None:
                # plain callables (and plans with unslottable literals)
                # journal opaque: the lifecycle and idempotency index
                # still replay; recovery skips the resubmit
                rec["opaque"] = True
            jrn.append_submit(rec)
        metrics.event(
            "serve.submit", query=q.query_id, tenant=tenant,
            priority=priority, budget_s=eff,
        )
        return q

    def _pressure_cause_locked(self, now: float,
                               incoming_cost: Optional[float] = None,
                               ) -> Optional[str]:
        """The overload controller's trip decision: queue depth, queue
        age, memory-governor pressure, and (srjt-cache) predicted-cost
        forecast — admission-time only."""
        if self._max_queued > 0 and self._queued >= self._max_queued:
            return "pressure"
        from ..utils import knobs as _knobs

        budget = _knobs.get_float("SRJT_SERVE_FORECAST_BUDGET_SEC")
        if budget is not None and budget > 0:
            # admission-cost forecast: cached plans carry an observed
            # run-cost EWMA; when the PREDICTED seconds of work already
            # queued plus this query exceed the budget, shed NOW at
            # queue depth 1-2 instead of after the queue is deep —
            # depth-based control can't see that two queued monsters
            # are worse than ten queued trivia. Unknown costs count 0:
            # the forecast only ever sheds on what it has evidence for.
            queued_cost = sum(
                (q._predicted_cost_s or 0.0)
                for t in self._tenants.values() for q in t.q
            )
            if queued_cost + (incoming_cost or 0.0) > budget:
                return "forecast"
        if self._queued:
            # per-tenant FIFO: each lane's head is its oldest entry,
            # so the global oldest is a min over heads, not a full scan
            oldest = min(
                (t.q[0]._t_submit for t in self._tenants.values() if t.q),
                default=None,
            )
            if oldest is not None and now - oldest > self._max_queue_age_s:
                return "pressure"
        # memgov blocked admissions == the device budget is the
        # bottleneck — but only a REAL backlog makes that an overload
        # signal: with fewer queued queries than dispatch slots the
        # bounded queues exist precisely to absorb the wait (a
        # momentary byte-wait must not shed a trickle tenant with an
        # empty queue). Gauge is registry-direct, 0 when the governor
        # never armed.
        if self._queued >= self._slots:
            from .. import memgov

            if (memgov.is_enabled()
                    and self._reg().value("memgov.queue_depth", 0) > 0):
                return "pressure"
        return None

    def _evict_locked(self, t: Optional[_Tenant], incoming: QueryHandle,
                      cause: str) -> Optional[QueryHandle]:
        """Lowest-priority-first shedding: evict the lowest-priority
        (latest-arrived on ties) QUEUED query — from tenant ``t``, or
        anywhere when None — iff ``incoming`` strictly outranks it.
        The victim's handle is finished with Overloaded and counted,
        but its done event and shed event are the CALLER's to fire
        after the lock is released. Returns the victim, or None when
        the incoming query may not displace anyone."""
        pool = (
            list(t.q) if t is not None
            else [q for tt in self._tenants.values() for q in tt.q]
        )
        if not pool:
            return None
        victim = min(pool, key=lambda q: (q.priority, -q._t_submit))
        if victim.priority >= incoming.priority:
            return None
        self._finish_locked(
            victim, S_SHED,
            self._overloaded(
                f"query {victim.query_id} shed at admission ({cause}): "
                f"priority {victim.priority} displaced by {incoming.priority}",
                cause,
            ),
        )
        self._tenants[victim.tenant].shed += 1
        self._count_shed(cause)
        return victim

    def _journal_state(self, q: QueryHandle, state: str,
                       result: Any = None, cause: Optional[str] = None,
                       ) -> None:
        """Append one state-transition record for an admitted query
        (srjt-durable, ISSUE 20). One attribute read when the journal is
        off (``_jid`` is None); always called strictly OUTSIDE the
        dispatch lock — journal appends are fsync'd file I/O, governed
        by the same rule as every event write. A DONE record carries the
        result digest so a restarted coordinator answers the query's
        idempotency key without re-running it."""
        if q._jid is None:
            return
        from . import journal as journal_mod

        jrn = journal_mod.active()
        if jrn is None:
            return
        digest = None
        if state == S_DONE:
            digest = journal_mod.result_digest(result)
        jrn.append_state(q._jid, state, digest=digest, cause=cause)

    # -- completion bookkeeping ----------------------------------------------

    def _finish_locked(self, q: QueryHandle, state: str,
                       exc: Optional[BaseException],
                       result: Any = None) -> bool:
        """Move a handle to a final state (caller holds self._cond for
        queued handles; running handles complete through _complete).
        Deliberately does NOT set the done event: the caller releases
        waiters with ``q._done.set()`` only AFTER its counters/events
        land, so a ``result()`` returning implies the accounting is
        already visible."""
        if q._state in _FINAL:
            return False
        if q._state == S_QUEUED:
            try:
                self._tenants[q.tenant].q.remove(q)
            except (KeyError, ValueError):
                pass  # already popped by a dispatcher
            self._queued -= 1
            self._reg().gauge("serve.queued").set(self._queued)
        q._state = state
        q._exc = exc
        q._result = result
        return True

    def _complete(self, q: QueryHandle, state: str,
                  exc: Optional[BaseException], result: Any = None) -> None:
        reg = self._reg()
        with self._cond:
            if not self._finish_locked(q, state, exc, result):
                return
            t = self._tenants.get(q.tenant)  # pruned lanes: count global only
            if t is not None:
                if state == S_CANCELLED:
                    t.cancelled += 1
                elif state == S_DONE:
                    t.completed += 1
                else:
                    t.failed += 1
        if state == S_DONE:
            reg.counter("serve.completed").inc()
        elif state == S_CANCELLED:
            reg.counter("serve.cancelled").inc()
        else:
            reg.counter("serve.failed").inc()
        if metrics.is_enabled():
            now = self._clock()
            if q._t_dispatch is not None:
                metrics.histogram("serve.run_us").record(
                    (now - q._t_dispatch) * 1e6
                )
            metrics.histogram("serve.e2e_us").record(
                (now - q._t_submit) * 1e6
            )
        metrics.event(
            "serve.done", query=q.query_id, tenant=q.tenant, state=state,
            cls=None if exc is None else type(exc).__name__,
        )
        # durable terminal record BEFORE waiters wake: a result() that
        # returned implies the DONE digest is already journaled, so a
        # crash after the client read its answer still answers the
        # idempotency key by digest on restart
        self._journal_state(q, state, result=result)
        q._done.set()

    def _cancel(self, q: QueryHandle, reason: str) -> bool:
        where = None
        with self._cond:
            if q._state == S_QUEUED:
                q._token.cancel(reason)
                self._finish_locked(
                    q, S_CANCELLED,
                    DeadlineExceeded(
                        f"query {q.query_id}: cancelled in queue ({reason})"
                    ),
                )
                self._reg().counter("serve.cancelled").inc()
                t = self._tenants.get(q.tenant)
                if t is not None:
                    t.cancelled += 1
                where = "queued"
            elif q._state == S_RUNNING:
                # cooperative: the token rides the query's deadline
                # scope, so every layer beneath (retry backoffs,
                # shuffle escalations, sidecar socket deadlines) is a
                # cancel point — the slot frees when the fn unwinds
                q._token.cancel(reason)
                where = "running"
        if where is None:
            return False
        # event I/O outside the dispatch lock
        metrics.event(
            "serve.cancel", query=q.query_id, tenant=q.tenant,
            where=where, reason=reason,
        )
        if where == "queued":
            qt = q._trace
            if qt is not None:
                qt.annotate(cancel_reason=reason)
                qt.finish("cancelled")
            self._journal_state(q, S_CANCELLED, cause=reason)
            q._done.set()
        return True

    # -- the dispatcher ------------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            expired: List[QueryHandle] = []
            q = None
            exiting = False
            with self._cond:
                while True:
                    q = self._pop_locked(expired)
                    if q is not None:
                        break
                    if not self._open and not any(
                        t.q for t in self._tenants.values()
                    ):
                        exiting = True
                        break
                    if expired:
                        # flush the expiry events (file I/O) outside
                        # the lock before going back to sleep
                        break
                    # every wake condition notifies (submit, shutdown,
                    # slot release); the timeout is a safety net, not a
                    # poll — long enough that idle slots cost ~nothing
                    self._cond.wait(0.5)
                if q is not None:
                    q._state = S_RUNNING
                    q._t_dispatch = self._clock()
                    self._running += 1
                    self._inflight.add(q)
                    self._reg().gauge("serve.running").set(self._running)
            for e in expired:  # counters landed in-lock; events + wakeups here
                metrics.event(
                    "serve.expired_in_queue", query=e.query_id,
                    tenant=e.tenant, budget_s=e._budget_s,
                )
                if e._trace is not None:
                    e._trace.annotate(expired_in_queue=True)
                    e._trace.finish("expired")
                self._journal_state(e, S_EXPIRED)
                e._done.set()
            if q is None:
                if exiting:
                    return
                continue
            try:
                self._run(q)
            finally:
                with self._cond:
                    self._running -= 1
                    self._inflight.discard(q)
                    self._reg().gauge("serve.running").set(self._running)
                    self._cond.notify_all()

    def _pop_locked(self, expired_out: List[QueryHandle]) -> Optional[QueryHandle]:
        """Stride scheduling over the non-empty tenant lanes, expiring
        dead-budget entries on the way (they never dispatch). Expired
        handles are fully accounted here (state/exc/counters) but
        appended to ``expired_out`` — the caller fires their events and
        done wakeups after releasing the lock."""
        while True:
            best = None
            for t in self._tenants.values():
                if t.q and (best is None or t.pass_ < best.pass_):
                    best = t
            if best is None:
                return None
            q = best.q.popleft()
            if q._state != S_QUEUED:
                continue  # finished while queued (cancel/shed race)
            now = self._clock()
            if q._t_deadline is not None and now >= q._t_deadline:
                # expired while queued: counted, completed, never run —
                # accounting lands BEFORE the done event releases any
                # result() waiter
                self._queued -= 1
                self._reg().gauge("serve.queued").set(self._queued)
                q._state = S_EXPIRED
                q._exc = DeadlineExceeded(
                    f"query {q.query_id}: budget "
                    f"({q._budget_s:g}s) expired in queue"
                )
                self._reg().counter("serve.expired_in_queue").inc()
                self._reg().counter("serve.failed").inc()
                t = self._tenants.get(q.tenant)
                if t is not None:
                    t.expired += 1
                expired_out.append(q)
                continue
            self._queued -= 1
            self._reg().gauge("serve.queued").set(self._queued)
            # the floor is the PRE-increment pass (the minimum over
            # non-empty lanes): entering lanes seed from it, and a
            # post-increment floor would let one low-weight dispatch
            # (huge stride) vault it far ahead, starving every tenant
            # that enters at the floor behind the whole backlog
            self._pass_floor = best.pass_
            best.pass_ += best.stride
            return q

    def _run(self, q: QueryHandle) -> None:
        # srjt-trace (ISSUE 12): the slot thread installs the query's
        # trace context for the fn's whole dynamic extent (op spans,
        # memgov admission waits, pool requests, wire hops all nest
        # under it), records the queue wait as a closed span, and
        # finishes the root from the handle's final state AFTER the run
        # span closed — so the in-memory tree explain_last() renders is
        # complete before the recorder sees it.
        qt = q._trace
        if qt is None:
            self._run_inner(q)
            return
        with qt.activate():
            tracing.closed_span(
                "serve.queue_wait",
                max(q._t_dispatch - q._t_submit, 0.0),
                tenant=q.tenant,
            )
            try:
                with tracing.span(
                    "serve.run", query=q.query_id, tenant=q.tenant
                ):
                    self._run_inner(q)
            finally:
                qt.finish(_TRACE_STATUS.get(q._state, q._state))

    def _run_inner(self, q: QueryHandle) -> None:
        from .. import memgov

        if metrics.is_enabled():
            metrics.histogram("serve.queue_wait_us").record(
                (q._t_dispatch - q._t_submit) * 1e6
            )
        metrics.event(
            "serve.dispatch", query=q.query_id, tenant=q.tenant,
            wait_us=round((q._t_dispatch - q._t_submit) * 1e6, 1),
        )
        # DISPATCHED is journaled after-the-fact (the slot thread,
        # outside the dispatch lock): replay distinguishes queued-only
        # work from work that may have partially executed — both
        # resubmit, but the seam is visible in the replayed lifecycle
        self._journal_state(q, "dispatched")
        budget = None
        if q._t_deadline is not None:
            # remaining after the queue wait; an expiry between pop and
            # here still yields a valid (instantly done) scope
            budget = max(q._t_deadline - self._clock(), 1e-6)
        adm = None
        try:
            with deadline_mod.scope(budget, token=q._token) as d:
                d.check(f"serve.query.{q.query_id}")
                if q._memory_bytes is not None and memgov.is_enabled():
                    # whole-query pre-admission: inner op boundaries
                    # see the held admission and skip their own (the
                    # memgov nesting discipline)
                    adm = memgov.admit(
                        f"serve.{q.tenant}", (), {}, q._memory_bytes
                    )
                try:
                    res = q._fn(*q._args, **q._kwargs)
                finally:
                    if adm is not None:
                        adm.release()
            self._complete(q, S_DONE, None, res)
        except BaseException as e:  # srjt-lint: allow-broad-except(dispatch slot: EVERY query failure — taxonomy, host-side, even SystemExit from user code — must land in the handle for result() to re-raise, or the waiter hangs forever; the slot itself must survive to serve the next query, so nothing re-raises out of a worker thread)
            state = S_FAILED
            if isinstance(e, DeadlineExceeded) and q._token.cancelled():
                state = S_CANCELLED
            self._complete(q, state, e)

    # -- shutdown ------------------------------------------------------------

    def shutdown(self, drain: bool = True,
                 timeout_s: Optional[float] = None) -> bool:
        """Stop admitting (subsequent submits raise
        ``Overloaded(shutting_down)``) and JOIN every dispatch slot.
        ``drain=True`` runs the queue dry first; ``drain=False``
        completes every queued handle with ``Overloaded(shutting_down)``
        and trips every in-flight query's cancel token, then joins the
        unwinding slots. Returns True when no thread leaked
        (``timeout_s`` bounds the join; a False return leaves the
        scheduler in the leak report)."""
        shed_queued: List[QueryHandle] = []
        with self._cond:
            already = not self._open
            self._open = False
            if not drain:
                for t in self._tenants.values():
                    for q in [qq for qq in t.q if qq._state == S_QUEUED]:
                        self._finish_locked(
                            q, S_SHED,
                            self._overloaded(
                                f"query {q.query_id}: scheduler shutting "
                                "down", "shutting_down",
                            ),
                        )
                        t.shed += 1
                        self._count_shed("shutting_down")
                        shed_queued.append(q)
                for q in self._inflight:
                    q._token.cancel("scheduler shutdown")
            self._cond.notify_all()
        for q in shed_queued:  # event I/O + wakeups outside the lock
            self._shed_event(q.tenant, "shutting_down")
            _shed_trace(q._trace, "shutting_down")
            self._journal_state(q, S_SHED, cause="shutting_down")
            q._done.set()
        t_end = None if timeout_s is None else time.monotonic() + timeout_s
        for w in self._workers:
            w.join(
                None if t_end is None
                else max(t_end - time.monotonic(), 0.001)
            )
        leaked = [w.name for w in self._workers if w.is_alive()]
        if not leaked:
            with _live_lock:
                _LIVE.discard(self)
        if not already:
            metrics.event(
                "serve.shutdown", scheduler=self.name, drain=drain,
                leaked_threads=len(leaked),
            )
        return not leaked

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc == (None, None, None))

    # -- observability -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-clean state for stats_report / tests."""
        with self._cond:
            return {
                "name": self.name,
                "open": self._open,
                "slots": self._slots,
                "running": self._running,
                "queued": self._queued,
                "queue_depth": self._queue_depth,
                "max_queued": self._max_queued,
                "max_queue_age_s": self._max_queue_age_s,
                "tenants": {
                    t.name: {
                        "queued": len(t.q),
                        "weight": t.weight,
                        "submitted": t.submitted,
                        "completed": t.completed,
                        "failed": t.failed,
                        "shed": t.shed,
                        "expired": t.expired,
                        "cancelled": t.cancelled,
                    }
                    for t in self._tenants.values()
                },
            }


# ---------------------------------------------------------------------------
# process-wide default scheduler + leak accounting
# ---------------------------------------------------------------------------

_live_lock = threading.Lock()
_LIVE: set = set()
_ever_created = False
_default: Optional[Scheduler] = None
_default_lock = threading.Lock()


def scheduler(**kwargs) -> Scheduler:
    """The process-wide default scheduler (lazy; kwargs only apply on
    first creation)."""
    global _default
    sch = _default  # one unlocked read: a concurrent shutdown may null
    if sch is None or not sch._open:  # the global between two reads
        with _default_lock:
            sch = _default
            if sch is None or not sch._open:
                sch = _default = Scheduler(**kwargs)
    return sch


def submit(fn, *args, **kwargs) -> QueryHandle:
    """``serve.submit(...)``: submit to the default scheduler."""
    return scheduler().submit(fn, *args, **kwargs)


def shutdown_scheduler(drain: bool = True,
                       timeout_s: Optional[float] = None) -> None:
    """Tear down the default scheduler (tests, process exit)."""
    global _default
    with _default_lock:
        sch, _default = _default, None
    if sch is not None:
        sch.shutdown(drain=drain, timeout_s=timeout_s)


def live_scheduler_count() -> int:
    """Schedulers whose worker threads have not all been joined — the
    session-scoped leak assertion in tests/conftest.py reads this."""
    with _live_lock:
        return len(_LIVE)


def leak_report() -> List[str]:
    with _live_lock:
        scheds = list(_LIVE)
    return [
        f"{s.name}: open={s._open} queued={s._queued} "
        f"running={s._running} threads="
        f"{[w.name for w in s._workers if w.is_alive()]}"
        for s in scheds
    ]


def stats_section() -> Optional[dict]:
    """The ``serve`` section of runtime.stats_report(): None until a
    scheduler has ever existed (a stats poll never instantiates one),
    else the durable registry counters plus every live scheduler's
    snapshot."""
    if not _ever_created:
        return None
    reg = metrics.registry()
    out = {
        "submitted": reg.value("serve.submitted"),
        "completed": reg.value("serve.completed"),
        "failed": reg.value("serve.failed"),
        "cancelled": reg.value("serve.cancelled"),
        "expired_in_queue": reg.value("serve.expired_in_queue"),
        "shed_total": reg.value("serve.shed_total"),
        "shed": {c: reg.value(f"serve.shed.{c}") for c in SHED_CAUSES},
    }
    with _live_lock:
        scheds = list(_LIVE)
    out["schedulers"] = [s.snapshot() for s in scheds]
    return out

"""Durable query journal: crash-recoverable serving (srjt-durable, ISSUE 20).

Every failure domain BELOW the coordinator already recovers — pool
workers fail over (PR 5), ranks die and lineage-replay (PR 16), spills
rot and recompute (PR 18) — but the serving process itself was the
last single point of loss: a coordinator crash forgot every queued and
in-flight query and discarded the completed answers clients were about
to read. This module is the durable metadata that closes it, Spark's
WAL discipline applied to the serve tier:

- ``Scheduler.submit`` appends one fsync'd CRC-framed **submit record**
  (client idempotency key, parameterized plan fingerprint + literal
  bindings, tenant/priority/deadline/memory estimate) to a segmented
  append-only log under ``SRJT_JOURNAL_DIR`` before the handle is
  returned; **state records** (dispatched/done/failed/cancelled/shed/
  expired) follow after the fact, strictly outside the dispatch lock
  like every other event write. A DONE record carries the result's
  ``result_digest`` so a restarted coordinator answers a duplicate
  submission idempotently (``DigestAnswer``) instead of re-running it.
- **Replay** (at journal open, and via ``replay()`` for tests) walks
  the segments in order, applies submits then states (a dispatch-slot
  state write may land before the submitter's record under concurrency
  — replay is order-insensitive by construction), and TRUNCATES any
  torn tail: a short header, a truncated payload, or a CRC mismatch
  ends that segment (counted ``journal.truncated_records``; the live
  journal also physically truncates the tail so the directory never
  accumulates rot). Any byte-prefix of a valid journal replays to a
  consistent state — the property tests/test_durable.py holds at every
  boundary.
- **Recovery** (``recover``): journaled-but-incomplete queries are
  resubmitted through the plan cache's rebind path — the caller
  resolves each record's parameterized fingerprint to a template plan
  + tables, the journaled literal bindings are rebound in
  (``rebind_literals``), and the resubmission carries
  ``recovered=True`` so the flight recorder annotates the restart seam.

Failure posture: a journal WRITE failure (full disk, dead mount)
degrades — counted ``journal.append_failures``, the journal disarms —
to today's volatile serving, never blocking admission. With
``SRJT_JOURNAL_DIR`` unset the module is inert: no files, no fsync,
one env read per submit.

On-disk format, per segment (``seg-<n>.jrnl``)::

    SRJTJRN1 [u32 len][u32 crc][payload: len bytes of JSON] ...

CRC is utils/integrity's 32-bit checksum over the payload. Records
cross ``faultinj.maybe_torn("journal.append", frame)`` so the
``torn_write`` chaos kind tears them deterministically.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import faultinj, integrity, knobs, metrics

__all__ = [
    "QueryJournal",
    "JournalState",
    "DigestAnswer",
    "active",
    "reset",
    "replay",
    "result_digest",
    "recover",
    "stats_section",
]

_MAGIC = b"SRJTJRN1"
_HDR = struct.Struct("<II")  # payload len, payload crc

# terminal states: a jid at one of these never resubmits on recovery
TERMINAL = ("done", "failed", "cancelled", "shed", "expired")


def _registry():
    return metrics.registry()


class DigestAnswer:
    """The idempotent answer for a duplicate submission whose original
    completed before the crash: the journaled result digest, NOT the
    result bytes (the journal stores metadata, not data). A client
    holding the pre-crash result verifies it against ``digest``; one
    that lost the result resubmits under a FRESH idempotency key to
    recompute. ``QueryHandle.result()`` returns this sentinel for
    idempotency-key hits."""

    __slots__ = ("idempotency_key", "digest", "jid")

    def __init__(self, idempotency_key: str, digest: int, jid: str):
        self.idempotency_key = idempotency_key
        self.digest = int(digest)
        self.jid = jid

    def matches(self, value) -> bool:
        """True iff ``value`` digests to the journaled answer."""
        return result_digest(value) == self.digest

    def __repr__(self):
        return (f"DigestAnswer(idem={self.idempotency_key!r}, "
                f"digest=0x{self.digest:08x}, jid={self.jid})")


def result_digest(value) -> int:
    """Order-stable 32-bit digest of a query result (any jax pytree):
    chained CRC over the treedef rendering plus every leaf's dtype and
    bytes — two bit-identical results always agree, and that is the
    equality the restart acceptance gate asserts."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(value)
    crc = integrity.checksum(repr(treedef).encode())
    for leaf in leaves:
        try:
            arr = np.asarray(leaf)
            crc = integrity.checksum(str(arr.dtype).encode(), crc)
            crc = integrity.checksum(arr.tobytes(), crc)
        except (TypeError, ValueError):
            # a non-array leaf (exotic result object): its repr is the
            # best stable rendering available — still deterministic for
            # the bit-identical case the digest exists to certify
            crc = integrity.checksum(repr(leaf).encode(), crc)
    return crc


# ---------------------------------------------------------------------------
# replayed view
# ---------------------------------------------------------------------------


class JournalState:
    """The consistent state a journal prefix replays to: submit records
    by jid with their latest state, plus the idempotency-key index."""

    __slots__ = ("records", "replayed", "truncated", "segments")

    def __init__(self):
        # jid -> {"rec": submit record, "state": str, "digest": int|None,
        #          "cause": str|None}
        self.records: Dict[str, dict] = {}
        self.replayed = 0
        self.truncated = 0
        self.segments = 0

    def apply_submit(self, rec: dict) -> None:
        jid = rec.get("jid")
        if not jid:
            return
        self.records.setdefault(
            jid, {"rec": rec, "state": "submitted", "digest": None,
                  "cause": None}
        )["rec"] = rec

    def apply_state(self, rec: dict) -> None:
        jid = rec.get("jid")
        entry = self.records.get(jid)
        if entry is None:
            return  # state for a submit the torn tail ate: ignorable
        state = rec.get("state")
        if entry["state"] in TERMINAL:
            return  # terminal is sticky: replay never resurrects work
        entry["state"] = state
        if rec.get("digest") is not None:
            entry["digest"] = int(rec["digest"])
        if rec.get("cause") is not None:
            entry["cause"] = rec["cause"]

    def incomplete(self) -> List[dict]:
        """Submit records with no terminal state, deduplicated by
        idempotency key (two pre-crash submissions of one idem key
        resubmit once) — recovery's work list, in journal order."""
        seen_idem: set = set()
        out = []
        for entry in self.records.values():
            if entry["state"] in TERMINAL:
                continue
            idem = entry["rec"].get("idem")
            if idem is not None:
                if idem in seen_idem:
                    continue
                seen_idem.add(idem)
            out.append(entry["rec"])
        return out

    def done_digest(self, idempotency_key: str) -> Optional[Tuple[str, int]]:
        """(jid, digest) of the DONE record journaled under this
        idempotency key, or None — the duplicate-submission index."""
        for jid, entry in self.records.items():
            if (entry["rec"].get("idem") == idempotency_key
                    and entry["state"] == "done"
                    and entry["digest"] is not None):
                return jid, entry["digest"]
        return None

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for entry in self.records.values():
            out[entry["state"]] = out.get(entry["state"], 0) + 1
        return out


def _segment_files(path: str) -> List[str]:
    try:
        names = os.listdir(path)
    except OSError:
        return []
    return sorted(
        os.path.join(path, n) for n in names
        if n.startswith("seg-") and n.endswith(".jrnl")
    )


def _replay_segment(path: str, state: JournalState) -> int:
    """Apply one segment into ``state``; returns the byte offset of the
    first torn/invalid frame (== file size when the segment is clean),
    so the opener can physically truncate the tail. Submits apply in a
    first pass and states in a second: under concurrency a dispatch
    slot's state write may legally land before the submitter's record."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return 0
    state.segments += 1
    if raw[: len(_MAGIC)] != _MAGIC:
        state.truncated += 1
        return 0
    off = len(_MAGIC)
    frames: List[dict] = []
    while off < len(raw):
        if off + _HDR.size > len(raw):
            state.truncated += 1
            break
        ln, crc = _HDR.unpack_from(raw, off)
        payload = raw[off + _HDR.size: off + _HDR.size + ln]
        if len(payload) != ln or integrity.checksum(payload) != crc:
            state.truncated += 1
            break
        try:
            frames.append(json.loads(payload.decode()))
        except (UnicodeDecodeError, ValueError):
            state.truncated += 1
            break
        off += _HDR.size + ln
    for rec in frames:
        if rec.get("t") == "submit":
            state.apply_submit(rec)
            state.replayed += 1
    for rec in frames:
        if rec.get("t") == "state":
            state.apply_state(rec)
            state.replayed += 1
    return off


def replay(path: str) -> JournalState:
    """Pure read: replay every segment under ``path`` into a
    JournalState (no truncation, no counters) — the property tests'
    entry point; the live journal replays through the same frame walk."""
    state = JournalState()
    for seg in _segment_files(path):
        _replay_segment(seg, state)
    return state


# ---------------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------------


class QueryJournal:
    """Segmented fsync'd append-only journal under one directory. One
    instance per process (``active()``); appends are serialized by one
    lock (submitters and dispatch slots both write), and the in-memory
    JournalState is maintained live so idempotency lookups see both the
    pre-crash replay and this process's own completions."""

    def __init__(self, path: str, segment_bytes: Optional[int] = None,
                 fsync: Optional[bool] = None):
        self.path = path
        self._segment_bytes = int(
            knobs.get_int("SRJT_JOURNAL_SEGMENT_BYTES")
            if segment_bytes is None else segment_bytes
        )
        self._fsync = bool(
            knobs.get_bool("SRJT_JOURNAL_FSYNC") if fsync is None else fsync
        )
        self._lock = threading.Lock()
        self._file = None
        self._file_bytes = 0
        self._degraded = False
        self._closed = False
        os.makedirs(path, exist_ok=True)
        # replay what a predecessor left, physically truncating any torn
        # tail so the directory carries no rot forward
        self.state = JournalState()
        segs = _segment_files(path)
        for seg in segs:
            good = _replay_segment(seg, self.state)
            try:
                if good < os.path.getsize(seg):
                    with open(seg, "rb+") as f:
                        f.truncate(good)
            except OSError:
                pass
        reg = _registry()
        if self.state.replayed:
            reg.counter("journal.replays").inc()
            reg.counter("journal.replayed_records").inc(self.state.replayed)
        if self.state.truncated:
            reg.counter("journal.truncated_records").inc(self.state.truncated)
        # appends always open a FRESH segment: never write after a
        # predecessor's tail, torn or clean
        self._next_seg = 1 + max(
            (int(os.path.basename(s)[4:-5])
             for s in segs if os.path.basename(s)[4:-5].isdigit()),
            default=0,
        )

    # -- append path ---------------------------------------------------------

    def _open_segment_locked(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
        seg = os.path.join(self.path, f"seg-{self._next_seg:06d}.jrnl")
        self._next_seg += 1
        self._file = open(seg, "ab")
        if self._file.tell() == 0:
            self._file.write(_MAGIC)
            self._file.flush()
        self._file_bytes = self._file.tell()
        _registry().counter("journal.segments_opened").inc()

    def _append_locked(self, rec: dict) -> bool:
        try:
            payload = json.dumps(
                rec, separators=(",", ":"), sort_keys=True
            ).encode()
        except (TypeError, ValueError):
            # an unserializable binding slipped past the submit-side
            # sanitizer: journal the record opaque (replay still sees
            # the lifecycle; recovery skips the resubmit)
            slim = {k: v for k, v in rec.items()
                    if k not in ("pf", "bindings")}
            slim["opaque"] = True
            payload = json.dumps(
                slim, separators=(",", ":"), sort_keys=True, default=repr
            ).encode()
        frame = _HDR.pack(len(payload), integrity.checksum(payload)) + payload
        # torn-write chaos crossing: the frame may come back a PREFIX —
        # exactly what a crash mid-write(2) leaves for replay to truncate
        frame = faultinj.maybe_torn("journal.append", frame)
        try:
            if (self._file is None
                    or self._file_bytes + len(frame) > self._segment_bytes):
                self._open_segment_locked()
            self._file.write(frame)
            self._file.flush()
            if self._fsync:
                os.fsync(self._file.fileno())
            self._file_bytes += len(frame)
        except OSError as e:
            # the degrade contract: a sick journal volume costs the
            # durability posture, never an admission
            self._degraded = True
            _registry().counter("journal.append_failures").inc()
            metrics.event("journal.append_failed", error=str(e))
            try:
                if self._file is not None:
                    self._file.close()
            except OSError:
                pass
            self._file = None
            return False
        _registry().counter("journal.appends").inc()
        return True

    def append_submit(self, rec: dict) -> bool:
        """Append one submit record (the scheduler builds it; ``jid``
        required). Returns False when degraded/failed — the caller
        proceeds volatile either way."""
        if self._degraded or self._closed:  # srjt-race: allow-unguarded(single boolean fast-path poll; GIL-atomic, append re-checks nothing — a stale False only costs one harmless locked append)
            return False
        rec = dict(rec)
        rec["t"] = "submit"
        with self._lock:
            ok = self._append_locked(rec)
            if ok:
                self.state.apply_submit(rec)
        return ok

    def append_state(self, jid: str, state: str,
                     digest: Optional[int] = None,
                     cause: Optional[str] = None) -> bool:
        if self._degraded or self._closed:
            return False
        rec: dict = {"t": "state", "jid": jid, "state": state}
        if digest is not None:
            rec["digest"] = int(digest)
        if cause is not None:
            rec["cause"] = cause
        with self._lock:
            ok = self._append_locked(rec)
            if ok:
                self.state.apply_state(rec)
        return ok

    # -- lookups -------------------------------------------------------------

    def done_digest(self, idempotency_key: str) -> Optional[Tuple[str, int]]:
        with self._lock:
            return self.state.done_digest(idempotency_key)

    def incomplete(self) -> List[dict]:
        with self._lock:
            return self.state.incomplete()

    @property
    def degraded(self) -> bool:
        return self._degraded

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "degraded": self._degraded,
                "segments": self.state.segments,
                "replayed": self.state.replayed,
                "truncated": self.state.truncated,
                "states": self.state.counts(),
            }


# ---------------------------------------------------------------------------
# process-wide singleton (armed by SRJT_JOURNAL_DIR)
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_active: Optional[QueryJournal] = None
_ever_active = False


def active() -> Optional[QueryJournal]:
    """The process journal, or None when ``SRJT_JOURNAL_DIR`` is unset
    (one env read — the off posture's whole cost) or journal open
    failed (counted; volatile degrade, like an append failure)."""
    global _active, _ever_active
    d = knobs.get_str("SRJT_JOURNAL_DIR")
    if not d:
        return None
    j = _active
    if j is not None and j.path == d and not j._closed:
        return j
    with _active_lock:
        j = _active
        if j is None or j.path != d or j._closed:
            if j is not None and not j._closed:
                j.close()
            try:
                j = _active = QueryJournal(d)
                _ever_active = True
            except OSError as e:
                _registry().counter("journal.append_failures").inc()
                metrics.event("journal.open_failed", path=d, error=str(e))
                return None
    return j


def reset() -> None:
    """Close and discard the singleton (tests / shutdown)."""
    global _active
    with _active_lock:
        j, _active = _active, None
    if j is not None:
        j.close()


# ---------------------------------------------------------------------------
# recovery: rebind + resubmit journaled-but-incomplete work
# ---------------------------------------------------------------------------


def sanitize_bindings(bindings) -> Optional[list]:
    """Journal-side rendering of ParamFingerprint bindings: JSON-clean
    ``[tag, value, dtype_key]`` rows (numpy scalars collapse to Python
    natives; the tag re-coerces them on recovery). None when any value
    resists — the record is journaled opaque instead."""
    out = []
    for tag, value, dkey in bindings:
        if tag in ("int", "i32"):
            value = int(value)
        elif tag == "float":
            value = float(value)
        elif tag == "bool":
            value = bool(value)
        elif tag == "null":
            value = None
        else:
            return None
        out.append([tag, value, None if dkey is None else list(dkey)])
    return out


def _coerce(tag: str, value):
    """Recovery-side inverse of ``sanitize_bindings``: restore the
    exact value type class the tag pinned, so a rebound literal infers
    the same dtype the journaled plan carried."""
    if tag == "i32":
        import numpy as np

        return np.int32(value)
    if tag == "int":
        return int(value)
    if tag == "float":
        return float(value)
    if tag == "bool":
        return bool(value)
    return value


def rebind_for_record(template, rec: dict):
    """Rebind a template plan (same parameterized fingerprint) to the
    literal values a journaled submission carried. None when the record
    cannot be rebound soundly: fingerprint mismatch, binding arity
    drift, or an ambiguous slot (two template slots share one
    (tag, value, dtype) triple but want different journaled values —
    by-value rebinding cannot tell them apart)."""
    from ..plan.rewrites import parameterized_fingerprint, rebind_literals

    pf = parameterized_fingerprint(template)
    if rec.get("pf") != pf.key:
        return None
    journaled = rec.get("bindings") or []
    if len(journaled) != len(pf.bindings):
        return None
    mapping: dict = {}
    for (tag, old, dkey), row in zip(pf.bindings, journaled):
        jtag, jval = row[0], row[1]
        if jtag != tag:
            return None
        new = _coerce(jtag, jval)
        key = (tag, old, dkey)
        if key in mapping and not _values_equal(mapping[key], new):
            return None  # ambiguous slot: refuse, never guess
        mapping[key] = new
    return rebind_literals(template, mapping)


def _values_equal(a, b) -> bool:
    try:
        return type(a) is type(b) and bool(a == b)
    except Exception:  # srjt-lint: allow-broad-except(exotic literal __eq__ = not equal, never an error)
        return False


def recover(sched, resolver: Callable[[dict], Optional[tuple]],
            deadline_s: Optional[float] = None) -> dict:
    """Resubmit every journaled-but-incomplete query through
    ``sched.submit``. ``resolver(record) -> (template_plan, tables)``
    (or None to skip) is the caller's catalog: the journal stores the
    parameterized fingerprint and bindings, the application owns the
    plan shapes it serves. Resubmissions carry the original tenant/
    priority/memory estimate, the original idempotency key (a record
    whose twin already completed answers by digest instead of
    re-running — zero duplicate executions of DONE work), and
    ``recovered=True`` so the trace ring shows the restart seam.

    Returns ``{"resubmitted": [(record, handle)...], "skipped": n,
    "idempotent": n}``."""
    jrn = active()
    report = {"resubmitted": [], "skipped": 0, "idempotent": 0}
    if jrn is None:
        return report
    reg = _registry()
    for rec in jrn.incomplete():
        plan = None
        if not rec.get("opaque") and rec.get("pf"):
            resolved = resolver(rec)
            if resolved is not None:
                template, tables = resolved
                plan = rebind_for_record(template, rec)
        if plan is None:
            report["skipped"] += 1
            reg.counter("journal.recovery_skipped").inc()
            metrics.event("journal.recovery_skipped", jid=rec.get("jid"))
            continue
        handle = sched.submit(
            plan, tables,
            tenant=rec.get("tenant", "default"),
            priority=int(rec.get("priority", 0)),
            deadline_s=deadline_s,
            memory_bytes=rec.get("memory_bytes"),
            host_eligible=bool(rec.get("host_eligible", True)),
            idempotency_key=rec.get("idem"),
            recovered=True,
        )
        if isinstance(handle.result(0) if handle.done() else None,
                      DigestAnswer):
            report["idempotent"] += 1
        else:
            reg.counter("journal.recovered_resubmits").inc()
        report["resubmitted"].append((rec, handle))
    return report


def stats_section() -> Optional[dict]:
    """The journal half of the ``durability`` stats section: None until
    a journal was ever active this process (a stats poll never opens
    one), else the durable counters plus the live snapshot."""
    if not _ever_active:
        return None
    reg = _registry()
    out = {
        "appends": reg.value("journal.appends"),
        "append_failures": reg.value("journal.append_failures"),
        "replays": reg.value("journal.replays"),
        "replayed_records": reg.value("journal.replayed_records"),
        "truncated_records": reg.value("journal.truncated_records"),
        "idempotent_hits": reg.value("journal.idempotent_hits"),
        "recovered_resubmits": reg.value("journal.recovered_resubmits"),
        "recovery_skipped": reg.value("journal.recovery_skipped"),
    }
    j = _active
    if j is not None:
        out["journal"] = j.snapshot()
    return out

"""Concurrent multi-query serving runtime (ISSUE 8).

PRs 1-7 built the reliability substrate — retry/split, deadlines +
breaker, memgov admission, the crash-tolerant sidecar pool, the
integrity-checked data plane — but execution stayed one synchronous
query per process, so none of it was ever exercised *under
contention*. This package is the layer that arbitrates QUERIES the way
memgov arbitrates bytes (Theseus arbitrates work over its
data-movement-bounded executors the same way; PAPERS.md):

- **Scheduler** (`scheduler.py`): ``submit(fn_or_pipeline, tenant=,
  deadline_s=, priority=, memory_bytes=) -> QueryHandle`` executing
  concurrently across ``SRJT_SERVE_MAX_CONCURRENT`` dispatch slots
  that run straight into the existing op_boundary -> memgov admission
  -> sidecar-pool path, with each query's deadline/cancel token
  installed context-locally (the PR 3 machinery propagates it down
  every blocking layer).
- **Per-tenant QoS**: bounded per-tenant FIFO queues feeding a
  stride-scheduled (weighted-fair) dispatcher — one tenant's storm
  cannot starve another's trickle, and nothing buffers unboundedly.
- **Graceful degradation**: queue-full / dead-on-arrival / pressure /
  dark-pool submissions fast-fail AT ADMISSION with the retryable
  ``Overloaded`` taxonomy member carrying a ``retry_after_s`` hint —
  shedding is lowest-priority-first and never mid-flight.

``benchmarks/bench_serve.py`` is the proof harness: sustained QPS +
p50/p99/p999 for a mixed TPC q1/q6/q98 workload at fixed offered load,
plus a chaos tier (crash + hang + reject storm while serving) that
``ci/premerge.sh`` gates on zero wrong answers.

srjt-durable (ISSUE 20) adds **crash recoverability**: with
``SRJT_JOURNAL_DIR`` set, every admitted query is journaled (fsync'd,
CRC-framed) BEFORE its handle returns, state transitions are recorded
after-the-fact, and a restarted coordinator replays the journal —
answering duplicate idempotency keys from the recorded digest and
resubmitting journaled-but-incomplete work through
``journal.recover()``. See ``journal.py``.
"""

from . import journal
from .journal import DigestAnswer, recover
from .scheduler import (
    QueryHandle,
    Scheduler,
    SHED_CAUSES,
    leak_report,
    live_scheduler_count,
    scheduler,
    shutdown_scheduler,
    stats_section,
    submit,
)

__all__ = [
    "DigestAnswer",
    "QueryHandle",
    "Scheduler",
    "SHED_CAUSES",
    "journal",
    "recover",
    "leak_report",
    "live_scheduler_count",
    "scheduler",
    "shutdown_scheduler",
    "stats_section",
    "submit",
]

"""Benchmark "models": deterministic data generation and the
BASELINE.json stepping-stone query pipelines (GROUP BY SUM, TPC-H q1/q6,
TPC-DS q3/q95, XGBoost ETL->DMatrix). These are the workloads the
reference's surrounding stack runs; here they are first-class so the
framework can be benchmarked standalone, without a Spark driver.
"""

from . import compiled, datagen, tpch, tpcds, xgboost_bridge  # noqa: F401

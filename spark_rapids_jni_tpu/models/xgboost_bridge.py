"""Spark ETL -> XGBoost DMatrix bridge (BASELINE.json configs[4]).

The reference stack feeds XGBoost4J-Spark from GPU ColumnarBatches: the
plugin concatenates cudf columns into a device CSR/dense DMatrix without
a host round-trip. TPU-native equivalent, redesigned for the hardware:

- **dense, not CSR**: tree-method=hist consumes a quantized matrix; TPU
  VPU/MXU want dense tiles, and Criteo-style ETL output is dense after
  imputation anyway. Features land as one [N, F] float32 device array
  (bfloat16 optional for HBM headroom).
- **device quantile sketch**: per-feature cut points via a single sort
  per feature (XLA's sort is the TPU-canonical quantile path — no GK
  sketch needed when the batch fits the chip), then
- **binning**: vectorized searchsorted -> uint8/uint16 bin ids, the
  quantized DMatrix the hist algorithm trains on.

Nulls become NaN (XGBoost's missing marker) before sketch/binning;
NaN rows get the reserved missing bin (= num_bins).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar.dtype import TypeId
from ..ops import bitutils
from ..utils.dispatch import op_boundary

__all__ = ["DeviceDMatrix", "to_dmatrix", "quantile_cuts", "quantize"]


@dataclasses.dataclass
class DeviceDMatrix:
    """Device-resident training matrix.

    features: [N, F] float32 (NaN == missing)
    labels:   [N] float32 or None
    weights:  [N] float32 or None
    cuts:     [F, max_bins-1] float32 cut points (right-closed) or None
    binned:   [N, F] integer bin ids (missing -> num_bins) or None
    """

    features: jnp.ndarray
    feature_names: List[str]
    labels: Optional[jnp.ndarray] = None
    weights: Optional[jnp.ndarray] = None
    cuts: Optional[jnp.ndarray] = None
    binned: Optional[jnp.ndarray] = None

    @property
    def num_rows(self) -> int:
        return int(self.features.shape[0])

    @property
    def num_features(self) -> int:
        return int(self.features.shape[1])


def _column_as_f32(col: Column) -> jnp.ndarray:
    d = col.dtype
    if d.id == TypeId.STRING or d.id == TypeId.LIST:
        raise ValueError("encode string/list features before building a DMatrix")
    if d.id == TypeId.DECIMAL128:
        raise ValueError("cast DECIMAL128 features to float before building a DMatrix")
    if d.is_floating:
        vals = bitutils.float_view(col.data, d).astype(jnp.float32)
    else:
        vals = col.data.astype(jnp.float32)
    if col.validity is not None:
        vals = jnp.where(col.validity, vals, jnp.nan)
    return vals


@op_boundary("to_dmatrix")
def to_dmatrix(
    table: Table,
    feature_cols: Sequence[str],
    label_col: Optional[str] = None,
    weight_col: Optional[str] = None,
    max_bins: Optional[int] = None,
) -> DeviceDMatrix:
    """Build a device DMatrix from a Table; optionally sketch + quantize
    in the same call (one fused program per stage, no host round-trip)."""
    feats = jnp.stack([_column_as_f32(table.column(c)) for c in feature_cols], axis=1)
    labels = None if label_col is None else _column_as_f32(table.column(label_col))
    weights = None if weight_col is None else _column_as_f32(table.column(weight_col))
    dm = DeviceDMatrix(feats, list(feature_cols), labels, weights)
    if max_bins is not None:
        dm.cuts = quantile_cuts(feats, max_bins)
        dm.binned = quantize(feats, dm.cuts)
    return dm


@jax.jit
def _cuts_impl(features: jnp.ndarray, qs: jnp.ndarray) -> jnp.ndarray:
    # sort each feature column; NaNs sort to the end, index by valid count
    n = features.shape[0]
    srt = jnp.sort(features, axis=0)  # [N, F]
    valid = jnp.sum(~jnp.isnan(features), axis=0)  # [F]
    # quantile positions over the valid prefix only
    pos = qs[:, None] * jnp.maximum(valid[None, :] - 1, 0)  # [B-1, F]
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, jnp.maximum(valid[None, :] - 1, 0))
    frac = pos - lo
    col_idx = jnp.arange(features.shape[1])[None, :]
    a = srt[lo, col_idx]
    b = srt[hi, col_idx]
    cuts = a + (b - a) * frac  # linear interpolation, [B-1, F]
    # all-NaN feature: no valid rows -> emit +inf cuts (everything missing)
    cuts = jnp.where(valid[None, :] > 0, cuts, jnp.inf)
    return cuts.T  # [F, B-1]


def quantile_cuts(features: jnp.ndarray, max_bins: int) -> jnp.ndarray:
    """[F, max_bins-1] per-feature quantile cut points (hist sketch)."""
    if max_bins < 2:
        raise ValueError("max_bins must be >= 2")
    qs = jnp.linspace(0.0, 1.0, max_bins + 1)[1:-1].astype(jnp.float32)
    return _cuts_impl(features, qs)


@jax.jit
def _quantize_impl(features: jnp.ndarray, cuts: jnp.ndarray) -> jnp.ndarray:
    # bin id = number of cuts <= value (vectorized searchsorted over F)
    v = features[:, :, None]  # [N, F, 1]
    c = cuts[None, :, :]  # [1, F, B-1]
    ids = jnp.sum(v > c, axis=2).astype(jnp.int32)  # [N, F]
    missing_bin = cuts.shape[1] + 1
    return jnp.where(jnp.isnan(features), missing_bin, ids)


def quantize(features: jnp.ndarray, cuts: jnp.ndarray) -> jnp.ndarray:
    """[N, F] int32 bin ids in [0, num_bins]; missing -> num_bins."""
    return _quantize_impl(features, cuts)

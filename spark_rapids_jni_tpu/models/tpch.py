"""TPC-H stepping-stone queries (BASELINE.json configs[1]): q1 and q6 —
scan + filter + aggregate, no join. These are the first end-to-end
pipelines the RAPIDS accelerator offloads wholesale; here each runs as
one fused XLA program over device-resident Columns (expression eval ->
boolean mask -> sort-based group aggregate), with no host round-trip
between operators.

Data: a deterministic `lineitem` generator at a row-count "scale". Flag
columns are dictionary codes (int8), dates are TIMESTAMP_DAYS ints,
money columns FLOAT64 (bit-stored; see columnar/dtype.py FLOAT64 note).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..columnar import Table
from ..columnar import dtype as dt
from ..ops import copying
from ..ops.aggregate import groupby_aggregate
from ..ops.expressions import col, lit
from ..ops.sort import sort_by_key
from .datagen import Profile, create_random_table

__all__ = ["gen_lineitem", "q1", "q6"]

# l_returnflag codes: 0='A', 1='N', 2='R'; l_linestatus: 0='F', 1='O'
_LINEITEM_SCHEMA = [
    ("l_quantity", dt.FLOAT64, Profile(lower=1, upper=50)),
    ("l_extendedprice", dt.FLOAT64, Profile(lower=900, upper=105_000)),
    ("l_discount", dt.FLOAT64, Profile(lower=0.0, upper=0.1)),
    ("l_tax", dt.FLOAT64, Profile(lower=0.0, upper=0.08)),
    ("l_returnflag", dt.INT8, Profile(lower=0, upper=2)),
    ("l_linestatus", dt.INT8, Profile(lower=0, upper=1)),
    # days since 1992-01-01; TPC-H dates span 1992-01-01..1998-12-31 (~2557d)
    ("l_shipdate", dt.TIMESTAMP_DAYS, Profile(lower=0, upper=2557)),
]


def gen_lineitem(num_rows: int, seed: int = 42) -> Table:
    names = [n for n, _, _ in _LINEITEM_SCHEMA]
    dtypes = [d for _, d, _ in _LINEITEM_SCHEMA]
    profiles = {i: p for i, (_, _, p) in enumerate(_LINEITEM_SCHEMA)}
    return create_random_table(dtypes, num_rows, seed=seed, profiles=profiles, names=names)


# TPC-H dates as days since 1992-01-01 (the generator's epoch)
D_1998_12_01 = 2526
_D_1994_01_01 = 731
_D_1995_01_01 = 1096


def q1(lineitem: Table, delta_days: int = 90) -> Table:
    """Pricing summary report. SQL:

        SELECT l_returnflag, l_linestatus, sum(qty), sum(price),
               sum(price*(1-disc)), sum(price*(1-disc)*(1+tax)),
               avg(qty), avg(price), avg(disc), count(*)
        FROM lineitem WHERE l_shipdate <= date '1998-12-01' - delta days
        GROUP BY l_returnflag, l_linestatus ORDER BY 1, 2
    """
    cutoff = D_1998_12_01 - delta_days
    pred = (col("l_shipdate") <= lit(np.int32(cutoff))).evaluate(lineitem)
    t = copying.apply_boolean_mask(lineitem, pred)

    disc_price = (col("l_extendedprice") * (lit(1.0) - col("l_discount"))).evaluate(t)
    charge = (
        col("l_extendedprice") * (lit(1.0) - col("l_discount")) * (lit(1.0) + col("l_tax"))
    ).evaluate(t)
    values = Table(
        [
            t.column("l_quantity"),
            t.column("l_extendedprice"),
            disc_price,
            charge,
            t.column("l_discount"),
        ],
        ["qty", "price", "disc_price", "charge", "disc"],
    )
    keys = t.select(["l_returnflag", "l_linestatus"])
    out = groupby_aggregate(
        keys,
        values,
        [
            ("qty", "sum"),
            ("price", "sum"),
            ("disc_price", "sum"),
            ("charge", "sum"),
            ("qty", "mean"),
            ("price", "mean"),
            ("disc", "mean"),
            ("qty", "count_all"),
        ],
    )
    # groupby_aggregate returns key-sorted rows == ORDER BY 1, 2
    return out


def q6(lineitem: Table) -> float:
    """Forecasting revenue change. SQL:

        SELECT sum(l_extendedprice * l_discount) FROM lineitem
        WHERE l_shipdate >= date '1994-01-01'
          AND l_shipdate < date '1995-01-01'
          AND l_discount BETWEEN 0.05 AND 0.07
          AND l_quantity < 24

    Returns the scalar revenue.
    """
    pred = (
        (col("l_shipdate") >= lit(np.int32(_D_1994_01_01)))
        & (col("l_shipdate") < lit(np.int32(_D_1995_01_01)))
        & (col("l_discount") >= lit(0.05))
        & (col("l_discount") <= lit(0.07))
        & (col("l_quantity") < lit(24.0))
    ).evaluate(lineitem)
    t = copying.apply_boolean_mask(lineitem, pred)
    revenue = (col("l_extendedprice") * col("l_discount")).evaluate(t)
    ones = Table([revenue], ["revenue"])
    # single-group aggregate: constant key
    from ..columnar import Column
    import jax.numpy as jnp

    key = Table([Column(dt.INT8, data=jnp.zeros((t.num_rows,), jnp.int8))], ["g"])
    out = groupby_aggregate(key, ones, [("revenue", "sum")])
    if out.num_rows == 0:
        return 0.0
    # host bit-view: the exact f64 sum reads back losslessly (float_view
    # would round through f32 on TPU at this final scalar pull)
    return float(np.asarray(out.column("revenue_sum").data).view(np.float64)[0])

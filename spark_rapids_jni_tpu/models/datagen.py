"""Deterministic random table generation — the benchmark datagen tier.

TPU-native analog of the reference's nvbench input generator
(src/main/cpp/benchmarks/common/generate_input.hpp:33-35, 55-63 and
random_distribution_factory.cuh): seeded, per-type distribution profiles
(UNIFORM / NORMAL / GEOMETRIC), default value ranges per type, string
length distributions, null probability, and ``cycle_dtypes`` to build
wide tables from a small type list. Generation happens host-side with
numpy (like the reference, which generates on CPU and copies to device)
and lands as device-resident Columns.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import DType, TypeId

__all__ = [
    "Distribution",
    "Profile",
    "create_random_column",
    "create_random_table",
    "cycle_dtypes",
]


class Distribution(enum.Enum):
    UNIFORM = "uniform"
    NORMAL = "normal"
    GEOMETRIC = "geometric"


@dataclasses.dataclass
class Profile:
    """Per-column generation profile (generate_input.hpp distribution_params)."""

    distribution: Distribution = Distribution.UNIFORM
    lower: Optional[float] = None
    upper: Optional[float] = None
    null_probability: float = 0.0
    # string-only knobs
    min_length: int = 0
    max_length: int = 32


# Default ranges per type id (generate_input.hpp:86-117 equivalents,
# narrowed so sums stay exactly representable in the test oracles).
_DEFAULT_RANGE = {
    TypeId.INT8: (-100, 100),
    TypeId.INT16: (-10_000, 10_000),
    TypeId.INT32: (-1_000_000, 1_000_000),
    TypeId.INT64: (-1_000_000_000, 1_000_000_000),
    TypeId.UINT8: (0, 200),
    TypeId.UINT16: (0, 20_000),
    TypeId.UINT32: (0, 2_000_000),
    TypeId.UINT64: (0, 2_000_000_000),
    TypeId.FLOAT32: (-1000.0, 1000.0),
    TypeId.FLOAT64: (-1000.0, 1000.0),
    TypeId.BOOL8: (0, 1),
    TypeId.TIMESTAMP_DAYS: (0, 20_000),
    TypeId.DECIMAL32: (-(10**8), 10**8),
    TypeId.DECIMAL64: (-(10**15), 10**15),
    # float64 draw limits precision; stay within exactly-representable ints
    TypeId.DECIMAL128: (-(2**52), 2**52),
}


def _draw(rng: np.random.Generator, n: int, lo: float, hi: float, dist: Distribution) -> np.ndarray:
    if dist is Distribution.UNIFORM:
        return rng.uniform(lo, hi, n)
    if dist is Distribution.NORMAL:
        mid, spread = (lo + hi) / 2.0, max((hi - lo) / 6.0, 1e-9)
        return np.clip(rng.normal(mid, spread, n), lo, hi)
    if dist is Distribution.GEOMETRIC:
        span = max(hi - lo, 1e-9)
        g = rng.geometric(p=min(4.0 / span, 0.5), size=n).astype(np.float64)
        return np.clip(lo + g, lo, hi)
    raise ValueError(dist)


def create_random_column(
    d: DType, num_rows: int, rng: np.random.Generator, profile: Optional[Profile] = None
) -> Column:
    p = profile or Profile()
    tid = d.id

    validity = None
    if p.null_probability > 0:
        validity = jnp.asarray(rng.random(num_rows) >= p.null_probability)

    if tid == TypeId.STRING:
        lens = rng.integers(p.min_length, p.max_length + 1, num_rows).astype(np.int32)
        offsets = np.zeros(num_rows + 1, np.int32)
        np.cumsum(lens, out=offsets[1:])
        chars = rng.integers(97, 123, int(offsets[-1])).astype(np.uint8)  # a-z
        return Column(d, validity=validity, offsets=jnp.asarray(offsets), chars=jnp.asarray(chars))

    lo, hi = (p.lower, p.upper)
    if lo is None or hi is None:
        dlo, dhi = _DEFAULT_RANGE[tid]
        lo = dlo if lo is None else lo
        hi = dhi if hi is None else hi

    raw = _draw(rng, num_rows, lo, hi, p.distribution)
    if tid in (TypeId.FLOAT32, TypeId.FLOAT64):
        np_dt = np.float32 if tid == TypeId.FLOAT32 else np.float64
        from ..ops import bitutils

        data = bitutils.float_store(jnp.asarray(raw.astype(np_dt)), d)
        return Column(d, data=data, validity=validity)
    if tid == TypeId.DECIMAL128:
        ints = np.rint(raw).astype(np.int64)
        limbs = np.zeros((num_rows, 4), np.uint32)
        v = ints.astype(np.uint64)
        limbs[:, 0] = (v & 0xFFFFFFFF).astype(np.uint32)
        limbs[:, 1] = (v >> 32).astype(np.uint32)
        sign = (ints < 0).astype(np.uint32) * 0xFFFFFFFF
        limbs[:, 2] = sign
        limbs[:, 3] = sign
        return Column(d, data=jnp.asarray(limbs), validity=validity)

    ints = np.rint(raw).astype(np.int64)
    data = jnp.asarray(ints.astype(_np_of(d)))
    return Column(d, data=data, validity=validity)


def _np_of(d: DType):
    return np.dtype(jnp.dtype(d.jnp_dtype).name)


def cycle_dtypes(dtypes: Sequence[DType], num_cols: int) -> list:
    """Reference benchmarks build wide tables by cycling a dtype list
    (row_conversion.cpp:31-40)."""
    return [dtypes[i % len(dtypes)] for i in range(num_cols)]


def create_random_table(
    dtypes: Sequence[DType],
    num_rows: int,
    seed: int = 42,
    profiles: Optional[Dict[int, Profile]] = None,
    names: Optional[Sequence[str]] = None,
) -> Table:
    """Deterministic random table: same (dtypes, num_rows, seed) ->
    identical values on every host/run."""
    rng = np.random.default_rng(seed)
    cols = [
        create_random_column(d, num_rows, rng, (profiles or {}).get(i))
        for i, d in enumerate(dtypes)
    ]
    return Table(cols, names)

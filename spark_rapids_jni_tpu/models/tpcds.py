"""TPC-DS stepping-stone queries (BASELINE.json configs[2]/[3]): q3
(2-way hash join + sort) and q95 (multi-join with semi-join order
filtering — the exchange-heavy shape). Dimension values that are strings
in the spec are dictionary codes here (int lanes); the relational
algebra — joins, semi-joins, grouped aggregates, order-by — is the part
under test.

Deterministic generators produce a coherent star schema at a row-count
scale: foreign keys reference the generated dimension key ranges.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..ops import bitutils, copying
from ..ops.aggregate import groupby_aggregate
from ..ops.expressions import col, lit
from ..ops.join import left_semi_join
from ..ops.sort import sort_by_key

__all__ = ["gen_store", "gen_web", "q3", "q55", "q55_distributed", "q95"]



def _exact_total(col) -> float:
    """Exact grand total of a FLOAT64-bit column: one-segment windowed
    accumulation (jnp.sum on a float_view would re-round through f32 on
    TPU) + lossless host bit-view readback."""
    from ..ops.f64acc import segment_sum_f64bits

    bits = col.data
    if bits.shape[0] == 0:
        return 0.0
    seg = jnp.zeros((bits.shape[0],), jnp.int32)
    return float(np.asarray(segment_sum_f64bits(bits, seg, 1)).view(np.float64)[0])

def _int_col(arr: np.ndarray, d=dt.INT32) -> Column:
    return Column(d, data=jnp.asarray(arr.astype(np.dtype(jnp.dtype(d.jnp_dtype).name))))


def _f64_col(arr: np.ndarray) -> Column:
    return Column(dt.FLOAT64, data=bitutils.float_store(jnp.asarray(arr), dt.FLOAT64))


def gen_store(num_sales: int, seed: int = 42) -> Dict[str, Table]:
    """store_sales + date_dim + item star for q3."""
    rng = np.random.default_rng(seed)
    n_dates, n_items = 365 * 5, 1000

    date_dim = Table(
        [
            _int_col(np.arange(n_dates)),  # d_date_sk
            _int_col(1998 + np.arange(n_dates) // 365),  # d_year
            _int_col(1 + (np.arange(n_dates) % 365) // 31),  # d_moy (approx calendar)
        ],
        ["d_date_sk", "d_year", "d_moy"],
    )
    item = Table(
        [
            _int_col(np.arange(n_items)),  # i_item_sk
            _int_col(rng.integers(1, 1000, n_items)),  # i_manufact_id
            _int_col(rng.integers(1, 500, n_items)),  # i_brand_id (dict code)
            _int_col(rng.integers(1, 100, n_items)),  # i_manager_id
        ],
        ["i_item_sk", "i_manufact_id", "i_brand_id", "i_manager_id"],
    )
    store_sales = Table(
        [
            _int_col(rng.integers(0, n_dates, num_sales)),  # ss_sold_date_sk
            _int_col(rng.integers(0, n_items, num_sales)),  # ss_item_sk
            _f64_col(rng.uniform(1, 1000, num_sales).round(2)),  # ss_ext_sales_price
        ],
        ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"],
    )
    return {"store_sales": store_sales, "date_dim": date_dim, "item": item}


def q3(tables: Dict[str, Table], manufact_id: int = 128, month: int = 11) -> Table:
    """SELECT d_year, i_brand_id, sum(ss_ext_sales_price) sum_agg
    FROM date_dim, store_sales, item
    WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
      AND i_manufact_id = :m AND d_moy = :mo
    GROUP BY d_year, i_brand_id
    ORDER BY d_year, sum_agg DESC, i_brand_id
    """
    item = tables["item"]
    dates = tables["date_dim"]
    ss = tables["store_sales"]

    # the WHOLE stage — star joins (with build-side dim filters), group
    # keys, aggregate — lowers through ONE compiled program; the bounded
    # domains come from the DIMENSION tables (tiny, so the host sync is
    # cheap) — not hard-coded, so any caller-supplied star schema works
    year_lo = int(jnp.min(dates.column("d_year").data))
    year_hi = int(jnp.max(dates.column("d_year").data))
    n_brands = int(jnp.max(item.column("i_brand_id").data)) + 1
    n_dates = int(jnp.max(dates.column("d_date_sk").data)) + 1
    n_items = int(jnp.max(item.column("i_item_sk").data)) + 1
    agg = _q3_pipeline(
        year_lo, year_hi - year_lo + 1, n_brands, n_dates, n_items,
        int(manufact_id), int(month),
    )(ss, {"date_dim": dates, "item": item})
    agg = Table(
        [
            Column(dt.INT32, data=agg.column("year_idx").data + jnp.int32(year_lo)),
            agg.column("i_brand_id"),
            agg.column("ss_ext_sales_price_sum"),
        ],
        ["d_year", "i_brand_id", "ss_ext_sales_price_sum"],
    )
    # ORDER BY d_year asc, sum desc, brand asc
    order_keys = Table(
        [agg.column("d_year"), agg.column("ss_ext_sales_price_sum"), agg.column("i_brand_id")],
        ["d_year", "s", "b"],
    )
    return sort_by_key(agg, order_keys, ascending=[True, False, True])


import functools


@functools.lru_cache(maxsize=16)
def _q3_pipeline(year_lo: int, n_years: int, n_brands: int, n_dates: int, n_items: int,
                 manufact_id: int, month: int):
    from ..pipeline import Agg, GroupKey, JoinSpec, PlanSpec, compile_plan

    return compile_plan(
        PlanSpec(
            joins=(
                JoinSpec(
                    build="date_dim", probe_key="ss_sold_date_sk", build_key="d_date_sk",
                    num_keys=n_dates, payload=("d_year",),
                    build_filter=col("d_moy") == lit(np.int32(month)),
                ),
                JoinSpec(
                    build="item", probe_key="ss_item_sk", build_key="i_item_sk",
                    num_keys=n_items, payload=("i_brand_id",),
                    build_filter=col("i_manufact_id") == lit(np.int32(manufact_id)),
                ),
            ),
            project=(("year_idx", col("d_year") - lit(np.int32(year_lo))),),
            group_by=(GroupKey("year_idx", n_years), GroupKey("i_brand_id", n_brands)),
            aggregates=(Agg("ss_ext_sales_price", "sum", "ss_ext_sales_price_sum"),),
        )
    )




def q55(tables: Dict[str, Table], manager_id: int = 28, month: int = 11, year: int = 1999) -> Table:
    """TPC-DS q55 (brand revenue for one manager-month). SQL:

        SELECT i_brand_id, sum(ss_ext_sales_price) ext_price
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = :mgr AND d_moy = :moy AND d_year = :yr
        GROUP BY i_brand_id ORDER BY ext_price DESC, i_brand_id

    Exercises the SORT-MERGE JoinSpec lowering (num_keys=None): both
    star joins binary-search sorted build keys inside the one compiled
    program — no bounded-domain declaration anywhere, matching cudf's
    general hash join (SURVEY §2.8)."""
    item = tables["item"]
    dates = tables["date_dim"]
    ss = tables["store_sales"]
    n_brands = int(jnp.max(item.column("i_brand_id").data)) + 1
    agg = _q55_pipeline(n_brands, int(manager_id), int(month), int(year))(
        ss, {"date_dim": dates, "item": item}
    )
    order_keys = Table(
        [agg.column("ext_price"), agg.column("i_brand_id")], ["p", "b"]
    )
    return sort_by_key(agg, order_keys, ascending=[False, True])


@functools.lru_cache(maxsize=16)
def _q55_pipeline(n_brands: int, manager_id: int, month: int, year: int):
    from ..pipeline import Agg, GroupKey, JoinSpec, PlanSpec, compile_plan

    return compile_plan(
        PlanSpec(
            joins=(
                JoinSpec(
                    build="date_dim", probe_key="ss_sold_date_sk",
                    build_key="d_date_sk", num_keys=None,  # sort-merge
                    build_filter=(col("d_moy") == lit(month)) & (col("d_year") == lit(year)),
                ),
                JoinSpec(
                    build="item", probe_key="ss_item_sk",
                    build_key="i_item_sk", num_keys=None,  # sort-merge
                    payload=("i_brand_id",),
                    build_filter=col("i_manager_id") == lit(manager_id),
                ),
            ),
            group_by=(GroupKey("i_brand_id", n_brands),),
            aggregates=(Agg("ss_ext_sales_price", "sum", "ext_price"),),
        )
    )


def q55_distributed(tables: Dict[str, Table], mesh, manager_id: int = 28, month: int = 11, year: int = 1999) -> Table:
    """q55 on the Table-level distributed operators: filtered dim tables
    inner-join the fact across the mesh, then a distributed group-by.
    Must produce results identical to single-chip ``q55``."""
    from ..parallel.table_ops import distributed_groupby_table, distributed_join_table

    item = tables["item"]
    dates = tables["date_dim"]
    ss = tables["store_sales"]

    dsel = ((col("d_moy") == lit(month)) & (col("d_year") == lit(year))).evaluate(dates)
    d1 = copying.apply_boolean_mask(dates, dsel).select(["d_date_sk"])
    d1 = Table(d1.columns, ["ss_sold_date_sk"])
    isel = (col("i_manager_id") == lit(manager_id)).evaluate(item)
    i1 = copying.apply_boolean_mask(item, isel).select(["i_item_sk", "i_brand_id"])
    i1 = Table(i1.columns, ["ss_item_sk", "i_brand_id"])

    j1, o1 = distributed_join_table(ss, d1, on=["ss_sold_date_sk"], mesh=mesh, how="inner")
    j2, o2 = distributed_join_table(j1, i1, on=["ss_item_sk"], mesh=mesh, how="inner")
    if o1 or o2:
        raise RuntimeError("join capacity overflow — raise capacity")
    agg, o3 = distributed_groupby_table(
        j2, ["i_brand_id"], [("ss_ext_sales_price", "sum", "ext_price")], mesh
    )
    if o3:
        raise RuntimeError("groupby capacity overflow — raise group_capacity")
    order_keys = Table([agg.column("ext_price"), agg.column("i_brand_id")], ["p", "b"])
    return sort_by_key(agg, order_keys, ascending=[False, True])

def gen_web(num_sales: int, seed: int = 7) -> Dict[str, Table]:
    """web_sales + web_returns + date_dim for q95. Orders have 1-4 line
    items; some span multiple warehouses; some are returned."""
    rng = np.random.default_rng(seed)
    n_orders = max(num_sales // 2, 1)
    n_dates = 365 * 5

    order_of_row = rng.integers(0, n_orders, num_sales)
    web_sales = Table(
        [
            _int_col(order_of_row),  # ws_order_number
            _int_col(rng.integers(0, 15, num_sales)),  # ws_warehouse_sk
            _int_col(rng.integers(0, n_dates, num_sales)),  # ws_ship_date_sk
            _f64_col(rng.uniform(1, 100, num_sales).round(2)),  # ws_ext_ship_cost
            _f64_col(rng.uniform(-50, 200, num_sales).round(2)),  # ws_net_profit
        ],
        ["ws_order_number", "ws_warehouse_sk", "ws_ship_date_sk", "ws_ext_ship_cost", "ws_net_profit"],
    )
    returned = rng.choice(n_orders, size=max(n_orders // 10, 1), replace=False)
    web_returns = Table([_int_col(returned)], ["wr_order_number"])
    date_dim = Table([_int_col(np.arange(n_dates))], ["d_date_sk"])
    return {"web_sales": web_sales, "web_returns": web_returns, "date_dim": date_dim}


def q95(tables: Dict[str, Table], ship_lo: int = 400, ship_hi: int = 460) -> dict:
    """Returned-order shipping report. SQL shape:

        WITH ws_wh AS (SELECT ws_order_number FROM web_sales
                       GROUP BY ws_order_number
                       HAVING count(distinct ws_warehouse_sk) > 1)
        SELECT count(distinct ws_order_number), sum(ws_ext_ship_cost),
               sum(ws_net_profit)
        FROM web_sales ws1
        WHERE ws_ship_date_sk BETWEEN :lo AND :hi
          AND ws_order_number IN (SELECT * FROM ws_wh)
          AND ws_order_number IN (SELECT wr_order_number FROM web_returns)

    The IN-subqueries run as true left-semi joins (the plan Spark
    produces for IN; ops.join.left_semi_join).
    """
    ws = tables["web_sales"]

    # ws_wh: orders shipped from >1 distinct warehouse == per-order
    # min(warehouse) != max(warehouse)
    per_order = groupby_aggregate(
        ws.select(["ws_order_number"]),
        ws.select(["ws_warehouse_sk"]),
        [("ws_warehouse_sk", "min"), ("ws_warehouse_sk", "max")],
    )
    multi = (col("ws_warehouse_sk_min") != col("ws_warehouse_sk_max")).evaluate(per_order)
    ws_wh = copying.apply_boolean_mask(per_order, multi).select(["ws_order_number"])

    # returned orders (no dedup needed: semi-join multiplicity is 0/1)
    wr = tables["web_returns"]
    wr_keys = Table(wr.select(["wr_order_number"]).columns, ["ws_order_number"])

    pred = (
        (col("ws_ship_date_sk") >= lit(np.int32(ship_lo)))
        & (col("ws_ship_date_sk") <= lit(np.int32(ship_hi)))
    ).evaluate(ws)
    ws1 = copying.apply_boolean_mask(ws, pred)
    ws1 = left_semi_join(ws1, ws_wh, on=["ws_order_number"])
    ws1 = left_semi_join(ws1, wr_keys, on=["ws_order_number"])

    per = groupby_aggregate(
        ws1.select(["ws_order_number"]),
        ws1.select(["ws_ext_ship_cost", "ws_net_profit"]),
        [("ws_ext_ship_cost", "sum"), ("ws_net_profit", "sum")],
    )
    return {
        "order_count": int(per.num_rows),
        "total_shipping_cost": _exact_total(per.column("ws_ext_ship_cost_sum")),
        "total_net_profit": _exact_total(per.column("ws_net_profit_sum")),
    }


def q95_distributed(tables: Dict[str, Table], mesh, ship_lo: int = 400, ship_hi: int = 460) -> dict:
    """q95 on the Table-level distributed operators (parallel/table_ops):
    the same plan as ``q95`` with every exchange-bearing step — both
    groupbys and both semi-joins — running as shuffled shard_map programs
    over the mesh. Filters and the tiny post-aggregation arithmetic stay
    local, exactly like Spark keeps narrow transformations pipelined.
    Must produce results identical to single-chip ``q95``."""
    from ..parallel.table_ops import distributed_groupby_table, distributed_join_table

    ws = tables["web_sales"]

    per_order, ovf = distributed_groupby_table(
        ws, ["ws_order_number"],
        [("ws_warehouse_sk", "min", "ws_warehouse_sk_min"),
         ("ws_warehouse_sk", "max", "ws_warehouse_sk_max")],
        mesh,
    )
    if ovf:
        raise RuntimeError("groupby capacity overflow — raise group_capacity")
    multi = (col("ws_warehouse_sk_min") != col("ws_warehouse_sk_max")).evaluate(per_order)
    ws_wh = copying.apply_boolean_mask(per_order, multi).select(["ws_order_number"])

    wr = tables["web_returns"]
    wr_keys = Table(wr.select(["wr_order_number"]).columns, ["ws_order_number"])

    pred = (
        (col("ws_ship_date_sk") >= lit(np.int32(ship_lo)))
        & (col("ws_ship_date_sk") <= lit(np.int32(ship_hi)))
    ).evaluate(ws)
    ws1 = copying.apply_boolean_mask(ws, pred)
    ws1, o1 = distributed_join_table(ws1, ws_wh, on=["ws_order_number"], mesh=mesh, how="left_semi")
    ws1, o2 = distributed_join_table(ws1, wr_keys, on=["ws_order_number"], mesh=mesh, how="left_semi")
    if o1 or o2:
        raise RuntimeError("join capacity overflow — raise capacity")

    per, o3 = distributed_groupby_table(
        ws1, ["ws_order_number"],
        [("ws_ext_ship_cost", "sum", "ws_ext_ship_cost_sum"),
         ("ws_net_profit", "sum", "ws_net_profit_sum")],
        mesh,
    )
    if o3:
        raise RuntimeError("groupby capacity overflow — raise group_capacity")
    return {
        "order_count": int(per.num_rows),
        "total_shipping_cost": _exact_total(per.column("ws_ext_ship_cost_sum")),
        "total_net_profit": _exact_total(per.column("ws_net_profit_sum")),
    }

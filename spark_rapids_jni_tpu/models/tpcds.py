"""TPC-DS stepping-stone queries (BASELINE.json configs[2]/[3]): q3
(2-way hash join + sort) and q95 (multi-join with semi-join order
filtering — the exchange-heavy shape). Dimension values that are strings
in the spec are dictionary codes here (int lanes); the relational
algebra — joins, semi-joins, grouped aggregates, order-by — is the part
under test.

Deterministic generators produce a coherent star schema at a row-count
scale: foreign keys reference the generated dimension key ranges.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..ops import bitutils, copying
from ..ops.aggregate import groupby_aggregate
from ..ops.expressions import col, lit
from ..ops.join import left_semi_join
from ..ops.sort import sort_by_key

__all__ = [
    "gen_store", "gen_store_wide", "gen_web",
    "q3", "q7", "q7_distributed", "q19", "q19_distributed",
    "q42", "q52", "q52_distributed", "q55", "q55_distributed",
    "q94", "q94_distributed", "q95", "q98",
]



def _exact_total(col) -> float:
    """Exact grand total of a FLOAT64-bit column: one-segment windowed
    accumulation (jnp.sum on a float_view would re-round through f32 on
    TPU) + lossless host bit-view readback."""
    from ..ops.f64acc import segment_sum_f64bits

    bits = col.data
    if bits.shape[0] == 0:
        return 0.0
    seg = jnp.zeros((bits.shape[0],), jnp.int32)
    return float(np.asarray(segment_sum_f64bits(bits, seg, 1)).view(np.float64)[0])

def _int_col(arr: np.ndarray, d=dt.INT32) -> Column:
    return Column(d, data=jnp.asarray(arr.astype(np.dtype(jnp.dtype(d.jnp_dtype).name))))


def _f64_col(arr: np.ndarray) -> Column:
    return Column(dt.FLOAT64, data=bitutils.float_store(jnp.asarray(arr), dt.FLOAT64))


def gen_store(num_sales: int, seed: int = 42) -> Dict[str, Table]:
    """store_sales + date_dim + item star for q3."""
    rng = np.random.default_rng(seed)
    n_dates, n_items = 365 * 5, 1000

    date_dim = Table(
        [
            _int_col(np.arange(n_dates)),  # d_date_sk
            _int_col(1998 + np.arange(n_dates) // 365),  # d_year
            _int_col(1 + (np.arange(n_dates) % 365) // 31),  # d_moy (approx calendar)
        ],
        ["d_date_sk", "d_year", "d_moy"],
    )
    item = Table(
        [
            _int_col(np.arange(n_items)),  # i_item_sk
            _int_col(rng.integers(1, 1000, n_items)),  # i_manufact_id
            _int_col(rng.integers(1, 500, n_items)),  # i_brand_id (dict code)
            _int_col(rng.integers(1, 100, n_items)),  # i_manager_id
        ],
        ["i_item_sk", "i_manufact_id", "i_brand_id", "i_manager_id"],
    )
    store_sales = Table(
        [
            _int_col(rng.integers(0, n_dates, num_sales)),  # ss_sold_date_sk
            _int_col(rng.integers(0, n_items, num_sales)),  # ss_item_sk
            _f64_col(rng.uniform(1, 1000, num_sales).round(2)),  # ss_ext_sales_price
        ],
        ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"],
    )
    # drawn AFTER the fact columns so adding it (round 5, q42) left every
    # pre-existing column's random sequence untouched
    item = Table(
        list(item.columns) + [_int_col(rng.integers(1, 12, n_items))],  # i_category_id
        list(item.names) + ["i_category_id"],
    )
    return {"store_sales": store_sales, "date_dim": date_dim, "item": item}


def gen_store_wide(num_sales: int, seed: int = 42) -> Dict[str, Table]:
    """Full store-sales star for the q7/q19 class: fact + date_dim +
    item + customer_demographics + promotion + customer +
    customer_address + store. String dimension values (gender, zip
    prefixes, channel flags) are dictionary codes in int lanes, as
    everywhere in this tier."""
    rng = np.random.default_rng(seed)
    n_dates, n_items = 365 * 5, 1000
    n_cdemo, n_promo, n_cust, n_addr, n_store = 200, 50, 2000, 500, 20

    date_dim = Table(
        [
            _int_col(np.arange(n_dates)),  # d_date_sk
            _int_col(1998 + np.arange(n_dates) // 365),  # d_year
            _int_col(1 + (np.arange(n_dates) % 365) // 31),  # d_moy
        ],
        ["d_date_sk", "d_year", "d_moy"],
    )
    item = Table(
        [
            _int_col(np.arange(n_items)),  # i_item_sk
            _int_col(rng.permutation(n_items)),  # i_item_id (distinct code)
            _int_col(rng.integers(1, 500, n_items)),  # i_brand_id
            _int_col(rng.integers(1, 1000, n_items)),  # i_manufact_id
            _int_col(rng.integers(1, 100, n_items)),  # i_manager_id
        ],
        ["i_item_sk", "i_item_id", "i_brand_id", "i_manufact_id", "i_manager_id"],
    )
    customer_demographics = Table(
        [
            _int_col(np.arange(n_cdemo)),  # cd_demo_sk
            _int_col(rng.integers(0, 2, n_cdemo)),  # cd_gender (code: 1 = 'M')
            _int_col(rng.integers(0, 5, n_cdemo)),  # cd_marital_status (2 = 'S')
            _int_col(rng.integers(0, 7, n_cdemo)),  # cd_education_status (3 = College)
        ],
        ["cd_demo_sk", "cd_gender", "cd_marital_status", "cd_education_status"],
    )
    promotion = Table(
        [
            _int_col(np.arange(n_promo)),  # p_promo_sk
            _int_col(rng.integers(0, 2, n_promo)),  # p_channel_email (0 = 'N')
            _int_col(rng.integers(0, 2, n_promo)),  # p_channel_event (0 = 'N')
        ],
        ["p_promo_sk", "p_channel_email", "p_channel_event"],
    )
    customer = Table(
        [
            _int_col(np.arange(n_cust)),  # c_customer_sk
            _int_col(rng.integers(0, n_addr, n_cust)),  # c_current_addr_sk
        ],
        ["c_customer_sk", "c_current_addr_sk"],
    )
    customer_address = Table(
        [
            _int_col(np.arange(n_addr)),  # ca_address_sk
            _int_col(rng.integers(0, 300, n_addr)),  # ca_zip5 (5-digit prefix code)
        ],
        ["ca_address_sk", "ca_zip5"],
    )
    store = Table(
        [
            _int_col(np.arange(n_store)),  # s_store_sk
            _int_col(rng.integers(0, 300, n_store)),  # s_zip5
        ],
        ["s_store_sk", "s_zip5"],
    )
    store_sales = Table(
        [
            _int_col(rng.integers(0, n_dates, num_sales)),  # ss_sold_date_sk
            _int_col(rng.integers(0, n_items, num_sales)),  # ss_item_sk
            _int_col(rng.integers(0, n_cdemo, num_sales)),  # ss_cdemo_sk
            _int_col(rng.integers(0, n_promo, num_sales)),  # ss_promo_sk
            _int_col(rng.integers(0, n_cust, num_sales)),  # ss_customer_sk
            _int_col(rng.integers(0, n_store, num_sales)),  # ss_store_sk
            _int_col(rng.integers(1, 100, num_sales)),  # ss_quantity
            _f64_col(rng.uniform(1, 200, num_sales).round(2)),  # ss_list_price
            _f64_col(rng.uniform(0, 50, num_sales).round(2)),  # ss_coupon_amt
            _f64_col(rng.uniform(1, 150, num_sales).round(2)),  # ss_sales_price
            _f64_col(rng.uniform(1, 1000, num_sales).round(2)),  # ss_ext_sales_price
        ],
        [
            "ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk", "ss_promo_sk",
            "ss_customer_sk", "ss_store_sk", "ss_quantity", "ss_list_price",
            "ss_coupon_amt", "ss_sales_price", "ss_ext_sales_price",
        ],
    )
    # srjt-plan (ISSUE 14) star extensions — every new random column /
    # table is drawn AFTER all pre-existing draws (the q42 pattern
    # above), so the original columns' random sequences are untouched
    # and the earlier oracle tests stay bit-identical.
    n_hdemo, n_times = 100, 1440
    store = Table(
        list(store.columns) + [_int_col(rng.integers(0, 10, n_store))],  # s_state (code)
        list(store.names) + ["s_state"],
    )
    store_sales = Table(
        list(store_sales.columns) + [
            _int_col(rng.integers(0, max(num_sales // 8, 1), num_sales)),  # ss_ticket_number
            _int_col(rng.integers(0, n_hdemo, num_sales)),  # ss_hdemo_sk
            _int_col(rng.integers(0, n_times, num_sales)),  # ss_sold_time_sk
        ],
        list(store_sales.names) + ["ss_ticket_number", "ss_hdemo_sk", "ss_sold_time_sk"],
    )
    customer = Table(
        list(customer.columns) + [_int_col(rng.permutation(n_cust))],  # c_customer_id
        list(customer.names) + ["c_customer_id"],
    )
    household_demographics = Table(
        [
            _int_col(np.arange(n_hdemo)),  # hd_demo_sk
            _int_col(rng.integers(0, 10, n_hdemo)),  # hd_dep_count
            _int_col(rng.integers(0, 5, n_hdemo)),  # hd_vehicle_count
            _int_col(rng.integers(0, 6, n_hdemo)),  # hd_buy_potential (code)
        ],
        ["hd_demo_sk", "hd_dep_count", "hd_vehicle_count", "hd_buy_potential"],
    )
    time_dim = Table(  # one row per minute (deterministic, no rng cost)
        [
            _int_col(np.arange(n_times)),  # t_time_sk
            _int_col(np.arange(n_times) // 60),  # t_hour
            _int_col(np.arange(n_times) % 60),  # t_minute
        ],
        ["t_time_sk", "t_hour", "t_minute"],
    )
    date_dim = Table(  # derived day-of-week lane (deterministic)
        list(date_dim.columns) + [_int_col(np.arange(n_dates) % 7)],
        list(date_dim.names) + ["d_dow"],
    )
    return {
        "store_sales": store_sales,
        "date_dim": date_dim,
        "item": item,
        "customer_demographics": customer_demographics,
        "promotion": promotion,
        "customer": customer,
        "customer_address": customer_address,
        "store": store,
        "household_demographics": household_demographics,
        "time_dim": time_dim,
    }


def q3(tables: Dict[str, Table], manufact_id: int = 128, month: int = 11) -> Table:
    """SELECT d_year, i_brand_id, sum(ss_ext_sales_price) sum_agg
    FROM date_dim, store_sales, item
    WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
      AND i_manufact_id = :m AND d_moy = :mo
    GROUP BY d_year, i_brand_id
    ORDER BY d_year, sum_agg DESC, i_brand_id
    """
    item = tables["item"]
    dates = tables["date_dim"]
    ss = tables["store_sales"]

    # the WHOLE stage — star joins (with build-side dim filters), group
    # keys, aggregate — lowers through ONE compiled program; the bounded
    # domains come from the DIMENSION tables (tiny, so the host sync is
    # cheap) — not hard-coded, so any caller-supplied star schema works
    year_lo = int(jnp.min(dates.column("d_year").data))
    year_hi = int(jnp.max(dates.column("d_year").data))
    n_brands = int(jnp.max(item.column("i_brand_id").data)) + 1
    n_dates = int(jnp.max(dates.column("d_date_sk").data)) + 1
    n_items = int(jnp.max(item.column("i_item_sk").data)) + 1
    agg = _q3_pipeline(
        year_lo, year_hi - year_lo + 1, n_brands, n_dates, n_items,
        int(manufact_id), int(month),
    )(ss, {"date_dim": dates, "item": item})
    agg = Table(
        [
            Column(dt.INT32, data=agg.column("year_idx").data + jnp.int32(year_lo)),
            agg.column("i_brand_id"),
            agg.column("ss_ext_sales_price_sum"),
        ],
        ["d_year", "i_brand_id", "ss_ext_sales_price_sum"],
    )
    # ORDER BY d_year asc, sum desc, brand asc
    order_keys = Table(
        [agg.column("d_year"), agg.column("ss_ext_sales_price_sum"), agg.column("i_brand_id")],
        ["d_year", "s", "b"],
    )
    return sort_by_key(agg, order_keys, ascending=[True, False, True])


import functools


@functools.lru_cache(maxsize=16)
def _q3_pipeline(year_lo: int, n_years: int, n_brands: int, n_dates: int, n_items: int,
                 manufact_id: int, month: int):
    from ..pipeline import Agg, GroupKey, JoinSpec, PlanSpec, compile_plan

    return compile_plan(
        PlanSpec(
            joins=(
                JoinSpec(
                    build="date_dim", probe_key="ss_sold_date_sk", build_key="d_date_sk",
                    num_keys=n_dates, payload=("d_year",),
                    build_filter=col("d_moy") == lit(np.int32(month)),
                ),
                JoinSpec(
                    build="item", probe_key="ss_item_sk", build_key="i_item_sk",
                    num_keys=n_items, payload=("i_brand_id",),
                    build_filter=col("i_manufact_id") == lit(np.int32(manufact_id)),
                ),
            ),
            project=(("year_idx", col("d_year") - lit(np.int32(year_lo))),),
            group_by=(GroupKey("year_idx", n_years), GroupKey("i_brand_id", n_brands)),
            aggregates=(Agg("ss_ext_sales_price", "sum", "ss_ext_sales_price_sum"),),
        )
    )




def q7(
    tables: Dict[str, Table],
    gender: int = 1,
    marital: int = 2,
    education: int = 3,
    year: int = 2000,
) -> Table:
    """TPC-DS q7 — the 4-way star join with FLOAT64 AVG aggregates. SQL:

        SELECT i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
               avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
        FROM store_sales, customer_demographics, date_dim, item, promotion
        WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
          AND ss_cdemo_sk = cd_demo_sk AND ss_promo_sk = p_promo_sk
          AND cd_gender = :g AND cd_marital_status = :m
          AND cd_education_status = :e
          AND (p_channel_email = 'N' OR p_channel_event = 'N')
          AND d_year = :y
        GROUP BY i_item_id ORDER BY i_item_id

    All four dimension joins, the demographic/promotion/date filters,
    and the four EXACT means (integer mean via the limb divider, f64
    means via the windowed accumulator) lower through ONE compiled
    program."""
    item = tables["item"]
    n_item_ids = int(jnp.max(item.column("i_item_id").data)) + 1
    n_dates = int(jnp.max(tables["date_dim"].column("d_date_sk").data)) + 1
    n_items = int(jnp.max(item.column("i_item_sk").data)) + 1
    n_cdemo = int(jnp.max(tables["customer_demographics"].column("cd_demo_sk").data)) + 1
    n_promo = int(jnp.max(tables["promotion"].column("p_promo_sk").data)) + 1
    agg = _q7_pipeline(
        n_item_ids, n_dates, n_items, n_cdemo, n_promo,
        int(gender), int(marital), int(education), int(year),
    )(
        tables["store_sales"],
        {
            "date_dim": tables["date_dim"],
            "item": item,
            "customer_demographics": tables["customer_demographics"],
            "promotion": tables["promotion"],
        },
    )
    return sort_by_key(agg, agg.select(["i_item_id"]), ascending=[True])


@functools.lru_cache(maxsize=16)
def _q7_pipeline(n_item_ids: int, n_dates: int, n_items: int, n_cdemo: int,
                 n_promo: int, gender: int, marital: int, education: int, year: int):
    from ..pipeline import Agg, GroupKey, JoinSpec, PlanSpec, compile_plan

    return compile_plan(
        PlanSpec(
            joins=(
                JoinSpec(
                    build="date_dim", probe_key="ss_sold_date_sk", build_key="d_date_sk",
                    num_keys=n_dates,
                    build_filter=col("d_year") == lit(np.int32(year)),
                ),
                JoinSpec(
                    build="customer_demographics", probe_key="ss_cdemo_sk",
                    build_key="cd_demo_sk", num_keys=n_cdemo,
                    build_filter=(col("cd_gender") == lit(np.int32(gender)))
                    & (col("cd_marital_status") == lit(np.int32(marital)))
                    & (col("cd_education_status") == lit(np.int32(education))),
                ),
                JoinSpec(
                    build="promotion", probe_key="ss_promo_sk", build_key="p_promo_sk",
                    num_keys=n_promo,
                    build_filter=(col("p_channel_email") == lit(np.int32(0)))
                    | (col("p_channel_event") == lit(np.int32(0))),
                ),
                JoinSpec(
                    build="item", probe_key="ss_item_sk", build_key="i_item_sk",
                    num_keys=n_items, payload=("i_item_id",),
                ),
            ),
            group_by=(GroupKey("i_item_id", n_item_ids),),
            aggregates=(
                Agg("ss_quantity", "mean", "agg1"),
                Agg("ss_list_price", "mean", "agg2"),
                Agg("ss_coupon_amt", "mean", "agg3"),
                Agg("ss_sales_price", "mean", "agg4"),
            ),
        )
    )


def q7_distributed(
    tables: Dict[str, Table], mesh,
    gender: int = 1, marital: int = 2, education: int = 3, year: int = 2000,
) -> Table:
    """q7 on the distributed Table operators: pre-filtered dims join the
    sharded fact, then a distributed group-by with EXACT means (partial
    limb sums + counts merge across shards, one division at the end) —
    results must be BIT-identical to single-chip ``q7``."""
    from ..parallel.table_ops import distributed_groupby_table, distributed_join_table

    ss = tables["store_sales"]
    dsel = (col("d_year") == lit(np.int32(year))).evaluate(tables["date_dim"])
    d1 = copying.apply_boolean_mask(tables["date_dim"], dsel).select(["d_date_sk"])
    d1 = Table(d1.columns, ["ss_sold_date_sk"])
    cd = tables["customer_demographics"]
    csel = (
        (col("cd_gender") == lit(np.int32(gender)))
        & (col("cd_marital_status") == lit(np.int32(marital)))
        & (col("cd_education_status") == lit(np.int32(education)))
    ).evaluate(cd)
    c1 = copying.apply_boolean_mask(cd, csel).select(["cd_demo_sk"])
    c1 = Table(c1.columns, ["ss_cdemo_sk"])
    pr = tables["promotion"]
    psel = (
        (col("p_channel_email") == lit(np.int32(0)))
        | (col("p_channel_event") == lit(np.int32(0)))
    ).evaluate(pr)
    p1 = copying.apply_boolean_mask(pr, psel).select(["p_promo_sk"])
    p1 = Table(p1.columns, ["ss_promo_sk"])
    i1 = tables["item"].select(["i_item_sk", "i_item_id"])
    i1 = Table(i1.columns, ["ss_item_sk", "i_item_id"])

    j, o1 = distributed_join_table(ss, d1, on=["ss_sold_date_sk"], mesh=mesh, how="inner")
    j, o2 = distributed_join_table(j, c1, on=["ss_cdemo_sk"], mesh=mesh, how="inner")
    j, o3 = distributed_join_table(j, p1, on=["ss_promo_sk"], mesh=mesh, how="inner")
    j, o4 = distributed_join_table(j, i1, on=["ss_item_sk"], mesh=mesh, how="inner")
    if o1 or o2 or o3 or o4:
        raise RuntimeError("join capacity overflow — raise capacity")
    agg, o5 = distributed_groupby_table(
        j, ["i_item_id"],
        [
            ("ss_quantity", "mean", "agg1"),
            ("ss_list_price", "mean", "agg2"),
            ("ss_coupon_amt", "mean", "agg3"),
            ("ss_sales_price", "mean", "agg4"),
        ],
        mesh,
    )
    if o5:
        raise RuntimeError("groupby capacity overflow — raise group_capacity")
    return sort_by_key(agg, agg.select(["i_item_id"]), ascending=[True])


def q19(
    tables: Dict[str, Table], manager_id: int = 8, month: int = 11, year: int = 1998
) -> Table:
    """TPC-DS q19 — 5-way star join with a CROSS-DIMENSION inequality
    (customer zip != store zip) evaluated on joined payload columns. SQL:

        SELECT i_brand_id, i_manufact_id, sum(ss_ext_sales_price) ext_price
        FROM date_dim, store_sales, item, customer, customer_address, store
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = :mgr AND d_moy = :moy AND d_year = :yr
          AND ss_customer_sk = c_customer_sk
          AND c_current_addr_sk = ca_address_sk
          AND substr(ca_zip,1,5) <> substr(s_zip,1,5)
          AND ss_store_sk = s_store_sk
        GROUP BY i_brand_id, i_manufact_id
        ORDER BY ext_price DESC, i_brand_id, i_manufact_id

    The customer join's payload (c_current_addr_sk) becomes the NEXT
    join's probe key — chained payload-probe joins in one program — and
    the zip comparison runs as the plan filter over two payloads."""
    item = tables["item"]
    n_brands = int(jnp.max(item.column("i_brand_id").data)) + 1
    n_manufact = int(jnp.max(item.column("i_manufact_id").data)) + 1
    n_dates = int(jnp.max(tables["date_dim"].column("d_date_sk").data)) + 1
    n_items = int(jnp.max(item.column("i_item_sk").data)) + 1
    n_cust = int(jnp.max(tables["customer"].column("c_customer_sk").data)) + 1
    n_addr = int(jnp.max(tables["customer_address"].column("ca_address_sk").data)) + 1
    n_store = int(jnp.max(tables["store"].column("s_store_sk").data)) + 1
    agg = _q19_pipeline(
        n_brands, n_manufact, n_dates, n_items, n_cust, n_addr, n_store,
        int(manager_id), int(month), int(year),
    )(
        tables["store_sales"],
        {
            "date_dim": tables["date_dim"],
            "item": item,
            "customer": tables["customer"],
            "customer_address": tables["customer_address"],
            "store": tables["store"],
        },
    )
    order_keys = Table(
        [agg.column("ext_price"), agg.column("i_brand_id"), agg.column("i_manufact_id")],
        ["p", "b", "m"],
    )
    return sort_by_key(agg, order_keys, ascending=[False, True, True])


@functools.lru_cache(maxsize=16)
def _q19_pipeline(n_brands: int, n_manufact: int, n_dates: int, n_items: int,
                  n_cust: int, n_addr: int, n_store: int, manager_id: int,
                  month: int, year: int):
    from ..pipeline import Agg, GroupKey, JoinSpec, PlanSpec, compile_plan

    return compile_plan(
        PlanSpec(
            joins=(
                JoinSpec(
                    build="date_dim", probe_key="ss_sold_date_sk", build_key="d_date_sk",
                    num_keys=n_dates,
                    build_filter=(col("d_moy") == lit(np.int32(month)))
                    & (col("d_year") == lit(np.int32(year))),
                ),
                JoinSpec(
                    build="item", probe_key="ss_item_sk", build_key="i_item_sk",
                    num_keys=n_items, payload=("i_brand_id", "i_manufact_id"),
                    build_filter=col("i_manager_id") == lit(np.int32(manager_id)),
                ),
                JoinSpec(
                    build="customer", probe_key="ss_customer_sk",
                    build_key="c_customer_sk", num_keys=n_cust,
                    payload=("c_current_addr_sk",),
                ),
                JoinSpec(
                    # probe key is the PREVIOUS join's payload
                    build="customer_address", probe_key="c_current_addr_sk",
                    build_key="ca_address_sk", num_keys=n_addr, payload=("ca_zip5",),
                ),
                JoinSpec(
                    build="store", probe_key="ss_store_sk", build_key="s_store_sk",
                    num_keys=n_store, payload=("s_zip5",),
                ),
            ),
            filter=col("ca_zip5") != col("s_zip5"),
            group_by=(
                GroupKey("i_brand_id", n_brands),
                GroupKey("i_manufact_id", n_manufact),
            ),
            aggregates=(Agg("ss_ext_sales_price", "sum", "ext_price"),),
        )
    )


def q19_distributed(
    tables: Dict[str, Table], mesh,
    manager_id: int = 8, month: int = 11, year: int = 1998,
) -> Table:
    """q19 on the distributed Table operators; the zip inequality runs
    shard-local after the address/store payloads arrive. Results must be
    BIT-identical to single-chip ``q19``."""
    from ..parallel.table_ops import distributed_groupby_table, distributed_join_table

    ss = tables["store_sales"]
    dsel = (
        (col("d_moy") == lit(np.int32(month))) & (col("d_year") == lit(np.int32(year)))
    ).evaluate(tables["date_dim"])
    d1 = copying.apply_boolean_mask(tables["date_dim"], dsel).select(["d_date_sk"])
    d1 = Table(d1.columns, ["ss_sold_date_sk"])
    isel = (col("i_manager_id") == lit(np.int32(manager_id))).evaluate(tables["item"])
    i1 = copying.apply_boolean_mask(tables["item"], isel).select(
        ["i_item_sk", "i_brand_id", "i_manufact_id"]
    )
    i1 = Table(i1.columns, ["ss_item_sk", "i_brand_id", "i_manufact_id"])
    c1 = tables["customer"].select(["c_customer_sk", "c_current_addr_sk"])
    c1 = Table(c1.columns, ["ss_customer_sk", "c_current_addr_sk"])
    a1 = tables["customer_address"].select(["ca_address_sk", "ca_zip5"])
    a1 = Table(a1.columns, ["c_current_addr_sk", "ca_zip5"])
    s1 = tables["store"].select(["s_store_sk", "s_zip5"])
    s1 = Table(s1.columns, ["ss_store_sk", "s_zip5"])

    j, o1 = distributed_join_table(ss, d1, on=["ss_sold_date_sk"], mesh=mesh, how="inner")
    j, o2 = distributed_join_table(j, i1, on=["ss_item_sk"], mesh=mesh, how="inner")
    j, o3 = distributed_join_table(j, c1, on=["ss_customer_sk"], mesh=mesh, how="inner")
    j, o4 = distributed_join_table(j, a1, on=["c_current_addr_sk"], mesh=mesh, how="inner")
    j, o5 = distributed_join_table(j, s1, on=["ss_store_sk"], mesh=mesh, how="inner")
    if o1 or o2 or o3 or o4 or o5:
        raise RuntimeError("join capacity overflow — raise capacity")
    keep = (col("ca_zip5") != col("s_zip5")).evaluate(j)
    j = copying.apply_boolean_mask(j, keep)
    agg, o6 = distributed_groupby_table(
        j, ["i_brand_id", "i_manufact_id"],
        [("ss_ext_sales_price", "sum", "ext_price")], mesh,
    )
    if o6:
        raise RuntimeError("groupby capacity overflow — raise group_capacity")
    order_keys = Table(
        [agg.column("ext_price"), agg.column("i_brand_id"), agg.column("i_manufact_id")],
        ["p", "b", "m"],
    )
    return sort_by_key(agg, order_keys, ascending=[False, True, True])



def _attach_year_and_sort(agg: Table, year: int, key_col: str, order_cols, ascending) -> Table:
    """Shared epilogue of the q42/q52 reporting family: re-attach the
    constant d_year the year-filter consumed, then ORDER BY. One
    definition so the single-chip and distributed variants cannot
    drift apart (their bit-identity contract)."""
    agg = Table(
        [
            Column(dt.INT32, data=jnp.full((agg.num_rows,), year, jnp.int32)),
            agg.column(key_col),
            agg.column("ext_price"),
        ],
        ["d_year", key_col, "ext_price"],
    )
    order_keys = Table(
        [agg.column(c) for c in order_cols], [f"k{i}" for i in range(len(order_cols))]
    )
    return sort_by_key(agg, order_keys, ascending=list(ascending))


def q42(tables: Dict[str, Table], manager_id: int = 1, month: int = 11, year: int = 2000) -> Table:
    """TPC-DS q42 (category revenue for a manager-month): the q3 shape
    grouped by (d_year, i_category_id). SQL:

        SELECT d_year, i_category_id, sum(ss_ext_sales_price)
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = :mgr AND d_moy = :moy AND d_year = :yr
        GROUP BY d_year, i_category_id
        ORDER BY sum DESC, d_year, i_category_id
    """
    item = tables["item"]
    dates = tables["date_dim"]
    n_cats = int(jnp.max(item.column("i_category_id").data)) + 1
    agg = _q42_pipeline(n_cats, int(manager_id), int(month), int(year))(
        tables["store_sales"], {"date_dim": dates, "item": item}
    )
    return _attach_year_and_sort(
        agg, year, "i_category_id",
        ["ext_price", "d_year", "i_category_id"], [False, True, True],
    )


@functools.lru_cache(maxsize=16)
def _q42_pipeline(n_cats: int, manager_id: int, month: int, year: int):
    from ..pipeline import Agg, GroupKey, JoinSpec, PlanSpec, compile_plan

    return compile_plan(
        PlanSpec(
            joins=(
                JoinSpec(
                    build="date_dim", probe_key="ss_sold_date_sk",
                    build_key="d_date_sk", num_keys=None,  # sort-merge
                    build_filter=(col("d_moy") == lit(month)) & (col("d_year") == lit(year)),
                ),
                JoinSpec(
                    build="item", probe_key="ss_item_sk",
                    build_key="i_item_sk", num_keys=None,  # sort-merge
                    payload=("i_category_id",),
                    build_filter=col("i_manager_id") == lit(manager_id),
                ),
            ),
            group_by=(GroupKey("i_category_id", n_cats),),
            aggregates=(Agg("ss_ext_sales_price", "sum", "ext_price"),),
        )
    )


def q52(tables: Dict[str, Table], manager_id: int = 1, month: int = 11, year: int = 2000) -> Table:
    """TPC-DS q52 (brand revenue for a manager-month; q55's plan carrying
    d_year through). SQL:

        SELECT d_year, i_brand_id, sum(ss_ext_sales_price) ext_price
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = :mgr AND d_moy = :moy AND d_year = :yr
        GROUP BY d_year, i_brand_id ORDER BY d_year, ext_price DESC, i_brand_id
    """
    item = tables["item"]
    n_brands = int(jnp.max(item.column("i_brand_id").data)) + 1
    agg = _q55_pipeline(n_brands, int(manager_id), int(month), int(year))(
        tables["store_sales"], {"date_dim": tables["date_dim"], "item": item}
    )
    return _attach_year_and_sort(
        agg, year, "i_brand_id", ["d_year", "ext_price", "i_brand_id"], [True, False, True]
    )


def q52_distributed(
    tables: Dict[str, Table], mesh, manager_id: int = 1, month: int = 11, year: int = 2000
) -> Table:
    """q52 on the distributed Table operators (q55's exchange plan with
    the year column re-attached). BIT-identical to single-chip q52."""
    agg = q55_distributed(tables, mesh, manager_id=manager_id, month=month, year=year)
    return _attach_year_and_sort(
        agg, year, "i_brand_id", ["d_year", "ext_price", "i_brand_id"], [True, False, True]
    )


def q55(tables: Dict[str, Table], manager_id: int = 28, month: int = 11, year: int = 1999) -> Table:
    """TPC-DS q55 (brand revenue for one manager-month). SQL:

        SELECT i_brand_id, sum(ss_ext_sales_price) ext_price
        FROM date_dim, store_sales, item
        WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
          AND i_manager_id = :mgr AND d_moy = :moy AND d_year = :yr
        GROUP BY i_brand_id ORDER BY ext_price DESC, i_brand_id

    Exercises the SORT-MERGE JoinSpec lowering (num_keys=None): both
    star joins binary-search sorted build keys inside the one compiled
    program — no bounded-domain declaration anywhere, matching cudf's
    general hash join (SURVEY §2.8)."""
    item = tables["item"]
    dates = tables["date_dim"]
    ss = tables["store_sales"]
    n_brands = int(jnp.max(item.column("i_brand_id").data)) + 1
    agg = _q55_pipeline(n_brands, int(manager_id), int(month), int(year))(
        ss, {"date_dim": dates, "item": item}
    )
    order_keys = Table(
        [agg.column("ext_price"), agg.column("i_brand_id")], ["p", "b"]
    )
    return sort_by_key(agg, order_keys, ascending=[False, True])


@functools.lru_cache(maxsize=16)
def _q55_pipeline(n_brands: int, manager_id: int, month: int, year: int):
    from ..pipeline import Agg, GroupKey, JoinSpec, PlanSpec, compile_plan

    return compile_plan(
        PlanSpec(
            joins=(
                JoinSpec(
                    build="date_dim", probe_key="ss_sold_date_sk",
                    build_key="d_date_sk", num_keys=None,  # sort-merge
                    build_filter=(col("d_moy") == lit(month)) & (col("d_year") == lit(year)),
                ),
                JoinSpec(
                    build="item", probe_key="ss_item_sk",
                    build_key="i_item_sk", num_keys=None,  # sort-merge
                    payload=("i_brand_id",),
                    build_filter=col("i_manager_id") == lit(manager_id),
                ),
            ),
            group_by=(GroupKey("i_brand_id", n_brands),),
            aggregates=(Agg("ss_ext_sales_price", "sum", "ext_price"),),
        )
    )


def q55_distributed(tables: Dict[str, Table], mesh, manager_id: int = 28, month: int = 11, year: int = 1999) -> Table:
    """q55 on the Table-level distributed operators: filtered dim tables
    inner-join the fact across the mesh, then a distributed group-by.
    Must produce results identical to single-chip ``q55``."""
    from ..parallel.table_ops import distributed_groupby_table, distributed_join_table

    item = tables["item"]
    dates = tables["date_dim"]
    ss = tables["store_sales"]

    dsel = ((col("d_moy") == lit(month)) & (col("d_year") == lit(year))).evaluate(dates)
    d1 = copying.apply_boolean_mask(dates, dsel).select(["d_date_sk"])
    d1 = Table(d1.columns, ["ss_sold_date_sk"])
    isel = (col("i_manager_id") == lit(manager_id)).evaluate(item)
    i1 = copying.apply_boolean_mask(item, isel).select(["i_item_sk", "i_brand_id"])
    i1 = Table(i1.columns, ["ss_item_sk", "i_brand_id"])

    j1, o1 = distributed_join_table(ss, d1, on=["ss_sold_date_sk"], mesh=mesh, how="inner")
    j2, o2 = distributed_join_table(j1, i1, on=["ss_item_sk"], mesh=mesh, how="inner")
    if o1 or o2:
        raise RuntimeError("join capacity overflow — raise capacity")
    agg, o3 = distributed_groupby_table(
        j2, ["i_brand_id"], [("ss_ext_sales_price", "sum", "ext_price")], mesh
    )
    if o3:
        raise RuntimeError("groupby capacity overflow — raise group_capacity")
    order_keys = Table([agg.column("ext_price"), agg.column("i_brand_id")], ["p", "b"])
    return sort_by_key(agg, order_keys, ascending=[False, True])

def gen_web(num_sales: int, seed: int = 7) -> Dict[str, Table]:
    """web_sales + web_returns + date_dim for q95. Orders have 1-4 line
    items; some span multiple warehouses; some are returned."""
    rng = np.random.default_rng(seed)
    n_orders = max(num_sales // 2, 1)
    n_dates = 365 * 5

    order_of_row = rng.integers(0, n_orders, num_sales)
    web_sales = Table(
        [
            _int_col(order_of_row),  # ws_order_number
            _int_col(rng.integers(0, 15, num_sales)),  # ws_warehouse_sk
            _int_col(rng.integers(0, n_dates, num_sales)),  # ws_ship_date_sk
            _f64_col(rng.uniform(1, 100, num_sales).round(2)),  # ws_ext_ship_cost
            _f64_col(rng.uniform(-50, 200, num_sales).round(2)),  # ws_net_profit
        ],
        ["ws_order_number", "ws_warehouse_sk", "ws_ship_date_sk", "ws_ext_ship_cost", "ws_net_profit"],
    )
    returned = rng.choice(n_orders, size=max(n_orders // 10, 1), replace=False)
    web_returns = Table([_int_col(returned)], ["wr_order_number"])
    date_dim = Table([_int_col(np.arange(n_dates))], ["d_date_sk"])
    # srjt-plan (ISSUE 14) extensions for the q92 family — drawn AFTER
    # every pre-existing column, keeping the q94/q95 sequences intact
    n_items = 200
    web_sales = Table(
        list(web_sales.columns) + [
            _int_col(rng.integers(0, n_dates, num_sales)),  # ws_sold_date_sk
            _int_col(rng.integers(0, n_items, num_sales)),  # ws_item_sk
            _f64_col(rng.uniform(0, 100, num_sales).round(2)),  # ws_ext_discount_amt
        ],
        list(web_sales.names) + ["ws_sold_date_sk", "ws_item_sk", "ws_ext_discount_amt"],
    )
    item = Table(
        [
            _int_col(np.arange(n_items)),  # i_item_sk
            _int_col(rng.integers(1, 100, n_items)),  # i_manufact_id
        ],
        ["i_item_sk", "i_manufact_id"],
    )
    return {"web_sales": web_sales, "web_returns": web_returns,
            "date_dim": date_dim, "item": item}


def q98(tables: Dict[str, Table], month: int = 11, year: int = 2000) -> Table:
    """TPC-DS q98 shape — the WINDOW-RATIO reporting family (q12/q20/
    q98): item revenue with each item's share of its CLASS partition.
    SQL shape:

        SELECT i_category, i_class(-> brand here), sum(ss_ext_sales_price) itemrevenue,
               sum(ss_ext_sales_price) * 100 /
                 sum(sum(ss_ext_sales_price)) OVER (PARTITION BY i_category) revenueratio
        FROM store_sales, item, date_dim
        WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
          AND d_moy = :moy AND d_year = :yr
        GROUP BY i_category, i_class ORDER BY i_category, revenueratio

    Exercises the round-5 window tier (ops/window.window_aggregate)
    composed AFTER a compiled star-join aggregation: the partitioned
    sum runs the exact f64 accumulator, so the ratio's numerator and
    denominator are both correctly rounded."""
    from ..ops.window import window_aggregate

    item = tables["item"]
    n_cats = int(jnp.max(item.column("i_category_id").data)) + 1
    n_brands = int(jnp.max(item.column("i_brand_id").data)) + 1
    agg = _q98_pipeline(n_cats, n_brands, int(month), int(year))(
        tables["store_sales"], {"date_dim": tables["date_dim"], "item": item}
    )
    w = window_aggregate(
        agg, ["i_category_id"], [], [("itemrevenue", "sum", "cat_total")]
    )
    ratio = (
        (col("itemrevenue") * lit(100.0)) / col("cat_total")
    ).evaluate(w)
    out = Table(
        [
            w.column("i_category_id"),
            w.column("i_brand_id"),
            w.column("itemrevenue"),
            ratio,
        ],
        ["i_category_id", "i_brand_id", "itemrevenue", "revenueratio"],
    )
    order_keys = Table(
        [out.column("i_category_id"), out.column("revenueratio"), out.column("i_brand_id")],
        ["c", "r", "b"],
    )
    return sort_by_key(out, order_keys, ascending=[True, True, True])


@functools.lru_cache(maxsize=16)
def _q98_pipeline(n_cats: int, n_brands: int, month: int, year: int):
    from ..pipeline import Agg, GroupKey, JoinSpec, PlanSpec, compile_plan

    return compile_plan(
        PlanSpec(
            joins=(
                JoinSpec(
                    build="date_dim", probe_key="ss_sold_date_sk",
                    build_key="d_date_sk", num_keys=None,
                    build_filter=(col("d_moy") == lit(month)) & (col("d_year") == lit(year)),
                ),
                JoinSpec(
                    build="item", probe_key="ss_item_sk",
                    build_key="i_item_sk", num_keys=None,
                    payload=("i_category_id", "i_brand_id"),
                ),
            ),
            group_by=(
                GroupKey("i_category_id", n_cats),
                GroupKey("i_brand_id", n_brands),
            ),
            aggregates=(Agg("ss_ext_sales_price", "sum", "itemrevenue"),),
        )
    )


def _q95_family(tables: Dict[str, Table], returns_how: str, ship_lo: int, ship_hi: int, mesh=None) -> dict:
    """Shared plan of TPC-DS q95 (EXISTS returns) and q94 (NOT EXISTS
    returns): per-order multi-warehouse detection, ship-date filter,
    semi-join on the multi-warehouse set, then a semi (q95) or anti
    (q94) join on returned orders, per-order sums, exact totals. One
    definition so the four entry points cannot drift. ``mesh=None``
    runs single-chip ops; a mesh routes every exchange-bearing step
    through the distributed Table operators (results must be identical
    — the distributed tests pin it)."""
    ws = tables["web_sales"]

    if mesh is None:
        per_order = groupby_aggregate(
            ws.select(["ws_order_number"]),
            ws.select(["ws_warehouse_sk"]),
            [("ws_warehouse_sk", "min"), ("ws_warehouse_sk", "max")],
        )
    else:
        from ..parallel.table_ops import distributed_groupby_table

        per_order, ovf = distributed_groupby_table(
            ws, ["ws_order_number"],
            [("ws_warehouse_sk", "min", "ws_warehouse_sk_min"),
             ("ws_warehouse_sk", "max", "ws_warehouse_sk_max")],
            mesh,
        )
        if ovf:
            raise RuntimeError("groupby capacity overflow — raise group_capacity")
    multi = (col("ws_warehouse_sk_min") != col("ws_warehouse_sk_max")).evaluate(per_order)
    ws_wh = copying.apply_boolean_mask(per_order, multi).select(["ws_order_number"])

    wr = tables["web_returns"]
    wr_keys = Table(wr.select(["wr_order_number"]).columns, ["ws_order_number"])

    pred = (
        (col("ws_ship_date_sk") >= lit(np.int32(ship_lo)))
        & (col("ws_ship_date_sk") <= lit(np.int32(ship_hi)))
    ).evaluate(ws)
    ws1 = copying.apply_boolean_mask(ws, pred)
    if mesh is None:
        from ..ops.join import left_anti_join

        ws1 = left_semi_join(ws1, ws_wh, on=["ws_order_number"])
        join2 = left_anti_join if returns_how == "left_anti" else left_semi_join
        ws1 = join2(ws1, wr_keys, on=["ws_order_number"])
        per = groupby_aggregate(
            ws1.select(["ws_order_number"]),
            ws1.select(["ws_ext_ship_cost", "ws_net_profit"]),
            [("ws_ext_ship_cost", "sum"), ("ws_net_profit", "sum")],
        )
    else:
        from ..parallel.table_ops import distributed_groupby_table, distributed_join_table

        ws1, o1 = distributed_join_table(ws1, ws_wh, on=["ws_order_number"], mesh=mesh, how="left_semi")
        ws1, o2 = distributed_join_table(ws1, wr_keys, on=["ws_order_number"], mesh=mesh, how=returns_how)
        if o1 or o2:
            raise RuntimeError("join capacity overflow — raise capacity")
        per, o3 = distributed_groupby_table(
            ws1, ["ws_order_number"],
            [("ws_ext_ship_cost", "sum", "ws_ext_ship_cost_sum"),
             ("ws_net_profit", "sum", "ws_net_profit_sum")],
            mesh,
        )
        if o3:
            raise RuntimeError("groupby capacity overflow — raise group_capacity")
    return {
        "order_count": int(per.num_rows),
        "total_shipping_cost": _exact_total(per.column("ws_ext_ship_cost_sum")),
        "total_net_profit": _exact_total(per.column("ws_net_profit_sum")),
    }


def q94(tables: Dict[str, Table], ship_lo: int = 400, ship_hi: int = 460) -> dict:
    """TPC-DS q94 — q95's NOT EXISTS variant: returned orders EXCLUDED
    via a true left ANTI join (Spark's NOT EXISTS lowering)."""
    return _q95_family(tables, "left_anti", int(ship_lo), int(ship_hi))


def q94_distributed(tables: Dict[str, Table], mesh, ship_lo: int = 400, ship_hi: int = 460) -> dict:
    """q94 over the distributed Table operators; identical to
    single-chip ``q94`` (pinned by test)."""
    return _q95_family(tables, "left_anti", int(ship_lo), int(ship_hi), mesh=mesh)


def q95(tables: Dict[str, Table], ship_lo: int = 400, ship_hi: int = 460) -> dict:
    """Returned-order shipping report. SQL shape:

        WITH ws_wh AS (SELECT ws_order_number FROM web_sales
                       GROUP BY ws_order_number
                       HAVING count(distinct ws_warehouse_sk) > 1)
        SELECT count(distinct ws_order_number), sum(ws_ext_ship_cost),
               sum(ws_net_profit)
        FROM web_sales ws1
        WHERE ws_ship_date_sk BETWEEN :lo AND :hi
          AND ws_order_number IN (SELECT * FROM ws_wh)
          AND ws_order_number IN (SELECT wr_order_number FROM web_returns)

    The IN-subqueries run as true left-semi joins (the plan Spark
    produces for IN; ops.join.left_semi_join). Shares its plan body
    with q94 (_q95_family)."""
    return _q95_family(tables, "left_semi", int(ship_lo), int(ship_hi))


def q95_distributed(tables: Dict[str, Table], mesh, ship_lo: int = 400, ship_hi: int = 460) -> dict:
    """q95 on the Table-level distributed operators (parallel/table_ops):
    the same plan with every exchange-bearing step — both groupbys and
    both membership joins — running as shuffled shard_map programs over
    the mesh. Must produce results identical to single-chip ``q95``."""
    return _q95_family(tables, "left_semi", int(ship_lo), int(ship_hi), mesh=mesh)


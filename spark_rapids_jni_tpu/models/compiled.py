"""Fused (single-XLA-program) TPC pipelines.

The operator-tier q1/q6 (models/tpch.py) compose public ops, each an
independent dispatch — correct, but on a remote/TPU backend the per-op
round-trips dominate. These variants trace the WHOLE query into one
jitted program over the table's raw arrays: scan -> filter -> aggregate
with no host sync except the final small result. This is the execution
shape the plugin would use per ColumnarBatch (one compiled plan per
schema), and the one the benchmarks measure.

Numerical parity with the op-tier pipelines is pinned by tests.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Table
from ..columnar import dtype as dt
from ..ops import bitutils
from .tpch import D_1998_12_01, _D_1994_01_01, _D_1995_01_01

__all__ = ["q6_fused", "q1_fused", "q6_kernel_args", "q1_kernel_args", "_q6_kernel", "_q1_kernel"]


def _f64(table: Table, name: str) -> jnp.ndarray:
    return bitutils.float_view(table.column(name).data, dt.FLOAT64)


@jax.jit
def _q6_kernel(ship, disc, qty, price):
    pred = (
        (ship >= _D_1994_01_01)
        & (ship < _D_1995_01_01)
        & (disc >= 0.05)
        & (disc <= 0.07)
        & (qty < 24.0)
    )
    return jnp.sum(jnp.where(pred, price * disc, 0.0))


def q6_kernel_args(lineitem: Table) -> Tuple[jnp.ndarray, ...]:
    """The (ship, disc, qty, price) arrays _q6_kernel consumes — the ONE
    place the positional contract lives (benchmarks reuse it)."""
    return (
        lineitem.column("l_shipdate").data,
        _f64(lineitem, "l_discount"),
        _f64(lineitem, "l_quantity"),
        _f64(lineitem, "l_extendedprice"),
    )


def q6_fused(lineitem: Table) -> float:
    """TPC-H q6 as one program: predicate + masked sum, no row
    materialization at all (the filter never builds a filtered table)."""
    return float(np.asarray(_q6_kernel(*q6_kernel_args(lineitem))))


@partial(jax.jit, static_argnums=(7,))
def _q1_kernel(ship, rf, ls, qty, price, disc, tax, cutoff: int):
    keep = ship <= cutoff
    # 3 returnflags x 2 linestatus = 6 static groups: direct-indexed
    # segment reductions, no sort needed (the group domain is tiny and
    # known — the plugin's dictionary-coded flags make this exact)
    gid = jnp.where(keep, rf.astype(jnp.int32) * 2 + ls.astype(jnp.int32), 6)
    num = 7  # 6 real + 1 trash segment for filtered rows

    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)
    one = jnp.ones_like(qty)

    def seg(v):
        return jax.ops.segment_sum(v, gid, num_segments=num)[:6]

    qty_s, price_s, dp_s, ch_s, disc_s, n = (
        seg(qty), seg(price), seg(disc_price), seg(charge), seg(disc), seg(one),
    )
    cnt = jnp.maximum(n, 1.0)
    return qty_s, price_s, dp_s, ch_s, qty_s / cnt, price_s / cnt, disc_s / cnt, n


def q1_kernel_args(lineitem: Table, delta_days: int = 90):
    """The positional argument tuple _q1_kernel consumes (last element
    is the static cutoff)."""
    return (
        lineitem.column("l_shipdate").data,
        lineitem.column("l_returnflag").data,
        lineitem.column("l_linestatus").data,
        _f64(lineitem, "l_quantity"),
        _f64(lineitem, "l_extendedprice"),
        _f64(lineitem, "l_discount"),
        _f64(lineitem, "l_tax"),
        D_1998_12_01 - delta_days,
    )


def q1_fused(lineitem: Table, delta_days: int = 90):
    """TPC-H q1 as one program. Returns a dict of [6] arrays keyed like
    the op-tier output (rows ordered by (returnflag, linestatus))."""
    out = _q1_kernel(*q1_kernel_args(lineitem, delta_days))
    qty_s, price_s, dp_s, ch_s, qty_m, price_m, disc_m, n = (np.asarray(a) for a in out)
    return {
        "qty_sum": qty_s,
        "price_sum": price_s,
        "disc_price_sum": dp_s,
        "charge_sum": ch_s,
        "qty_mean": qty_m,
        "price_mean": price_m,
        "disc_mean": disc_m,
        "count": n.astype(np.int64),
    }

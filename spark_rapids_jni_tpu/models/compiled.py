"""Fused (single-XLA-program) TPC pipelines, built on the generic
compiled-plan mechanism (spark_rapids_jni_tpu.pipeline).

Round 1 hand-fused q1 and q6 with bespoke positional kernels; those are
now ~10-line PlanSpecs lowered through ``CompiledPipeline`` — the same
(plan, schema) -> one-XLA-program path the plugin execution model uses
for every offloaded stage. Numerical parity with the op-tier pipelines
(models/tpch.py) is pinned by tests.
"""

from __future__ import annotations

import numpy as np

from ..columnar import Table
from ..ops.expressions import col, lit
from ..pipeline import Agg, CompiledPipeline, GroupKey, PlanSpec, compile_plan
from .tpch import D_1998_12_01, _D_1994_01_01, _D_1995_01_01

__all__ = ["q6_fused", "q1_fused", "q6_pipeline", "q1_pipeline"]


def q6_pipeline() -> CompiledPipeline:
    """TPC-H q6: filter + masked revenue sum, zero row materialization."""
    return compile_plan(
        PlanSpec(
            filter=(
                (col("l_shipdate") >= lit(np.int32(_D_1994_01_01)))
                & (col("l_shipdate") < lit(np.int32(_D_1995_01_01)))
                & (col("l_discount") >= lit(0.05))
                & (col("l_discount") <= lit(0.07))
                & (col("l_quantity") < lit(24.0))
            ),
            project=(("revenue", col("l_extendedprice") * col("l_discount")),),
            aggregates=(Agg("revenue", "sum"),),
        )
    )


_Q6 = None


def q6_fused(lineitem: Table) -> float:
    global _Q6
    if _Q6 is None:
        _Q6 = q6_pipeline()
    out = _Q6(lineitem)
    return float(out.column("revenue_sum").to_pylist()[0] or 0.0)


def q1_pipeline(delta_days: int = 90) -> CompiledPipeline:
    """TPC-H q1: filtered grouped sums/means over the 3x2 dictionary
    domain of (returnflag, linestatus) — dense segments, no sort."""
    cutoff = D_1998_12_01 - delta_days
    disc_price = col("l_extendedprice") * (lit(1.0) - col("l_discount"))
    charge = (col("l_extendedprice") * (lit(1.0) - col("l_discount"))) * (
        lit(1.0) + col("l_tax")
    )
    return compile_plan(
        PlanSpec(
            filter=col("l_shipdate") <= lit(np.int32(cutoff)),
            project=(("disc_price", disc_price), ("charge", charge)),
            group_by=(GroupKey("l_returnflag", 3), GroupKey("l_linestatus", 2)),
            aggregates=(
                Agg("l_quantity", "sum", "qty_sum"),
                Agg("l_extendedprice", "sum", "price_sum"),
                Agg("disc_price", "sum", "disc_price_sum"),
                Agg("charge", "sum", "charge_sum"),
                Agg("l_quantity", "mean", "qty_mean"),
                Agg("l_extendedprice", "mean", "price_mean"),
                Agg("l_discount", "mean", "disc_mean"),
                Agg("l_quantity", "count_all", "count"),
            ),
        )
    )


_Q1 = {}


def q1_fused(lineitem: Table, delta_days: int = 90):
    """TPC-H q1 through the generic pipeline. Returns a dict of [6]
    arrays ordered by (returnflag, linestatus), dense over the domain
    (empty groups zero-filled), matching the round-1 contract."""
    pipe = _Q1.get(delta_days)
    if pipe is None:
        pipe = _Q1[delta_days] = q1_pipeline(delta_days)
    out = pipe(lineitem)
    rf = np.asarray(out.column("l_returnflag").data)
    ls = np.asarray(out.column("l_linestatus").data)
    slot = rf * 2 + ls
    res = {}
    for name in (
        "qty_sum", "price_sum", "disc_price_sum", "charge_sum",
        "qty_mean", "price_mean", "disc_mean",
    ):
        dense = np.zeros(6, np.float64)
        dense[slot] = [v or 0.0 for v in out.column(name).to_pylist()]
        res[name] = dense
    cnt = np.zeros(6, np.int64)
    cnt[slot] = out.column("count").to_pylist()
    res["count"] = cnt
    return res

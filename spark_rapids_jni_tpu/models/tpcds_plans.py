"""TPC-DS queries expressed as LOGICAL PLANS (srjt-plan, ISSUE 14).

Every query here was a QUERIES.md "lowers" entry — operator surface
present, assembly missing — and goes green through the plan compiler
ALONE: the function builds an IR tree that transliterates the SQL, and
``plan.compile_ir`` performs the rewrites (decorrelation, ROLLUP
expansion, set-op/EXISTS/HAVING lowering, pushdown) plus the fused
``CompiledPipeline`` lowering that the hand-built greens in
``models/tpcds.py`` encode by hand. Dictionary-coded int lanes stand in
for string dimension values, as everywhere in this tier; parameter
defaults are calibrated to the generators here, not to the spec's
literals — the RELATIONAL SHAPE (which joins, which rewrites, which
aggregates) is the part under test against pandas oracles.

Two hand-built greens (q3, q55) are also re-expressed as plans
(``q3_plan`` / ``q55_plan``): the compiler must reproduce their fused
pipelines' outputs BIT-identically (tests/test_plan_queries.py pins
it), which is the evidence the mechanical lowering matches the
hand-fused originals.

``PLAN_QUERIES`` is the registry the tests, the ledger, and the
ci/premerge.sh compiler tier iterate: name -> (generator, plan builder,
runner, default rows).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict

import numpy as np

from .. import plan as P
from ..columnar import Table
from ..columnar import dtype as dt
from .tpcds import _f64_col, _int_col, gen_store_wide, gen_web

__all__ = [
    "gen_store_returns", "gen_catalog", "gen_channels",
    "q1", "q8", "q9", "q10", "q13", "q15", "q20", "q26", "q27", "q28",
    "q30", "q32", "q34", "q35", "q38", "q39", "q43", "q48", "q65", "q69",
    "q73", "q87", "q88", "q92", "q96", "q3_plan", "q55_plan",
    "PLAN_QUERIES", "PlanQueryDef",
]


# ---------------------------------------------------------------------------
# generators (star schemas the gen_store/gen_web family does not cover)
# ---------------------------------------------------------------------------


def gen_store_returns(num_returns: int, seed: int = 21) -> Dict[str, Table]:
    """store_returns + date_dim + store + customer for the q1 family
    (per-customer return totals vs the per-store average)."""
    rng = np.random.default_rng(seed)
    n_dates, n_store, n_cust = 365 * 3, 12, 1500
    date_dim = Table(
        [_int_col(np.arange(n_dates)), _int_col(1998 + np.arange(n_dates) // 365)],
        ["d_date_sk", "d_year"],
    )
    store = Table(
        [_int_col(np.arange(n_store)), _int_col(rng.integers(0, 8, n_store))],
        ["s_store_sk", "s_state"],
    )
    customer = Table(
        [_int_col(np.arange(n_cust)), _int_col(rng.permutation(n_cust))],
        ["c_customer_sk", "c_customer_id"],
    )
    store_returns = Table(
        [
            _int_col(rng.integers(0, n_dates, num_returns)),  # sr_returned_date_sk
            _int_col(rng.integers(0, n_cust, num_returns)),  # sr_customer_sk
            _int_col(rng.integers(0, n_store, num_returns)),  # sr_store_sk
            _f64_col(rng.uniform(1, 500, num_returns).round(2)),  # sr_return_amt
        ],
        ["sr_returned_date_sk", "sr_customer_sk", "sr_store_sk", "sr_return_amt"],
    )
    return {"store_returns": store_returns, "date_dim": date_dim,
            "store": store, "customer": customer}


def gen_catalog(num_sales: int, seed: int = 23) -> Dict[str, Table]:
    """catalog_sales star for the q26 (q7 catalog twin) and q20
    (partition-ratio reporting) shapes."""
    rng = np.random.default_rng(seed)
    n_dates, n_items, n_cdemo, n_promo = 365 * 5, 800, 150, 40
    date_dim = Table(
        [
            _int_col(np.arange(n_dates)),
            _int_col(1998 + np.arange(n_dates) // 365),
            _int_col(1 + (np.arange(n_dates) % 365) // 31),
        ],
        ["d_date_sk", "d_year", "d_moy"],
    )
    item = Table(
        [
            _int_col(np.arange(n_items)),  # i_item_sk
            _int_col(rng.permutation(n_items)),  # i_item_id
            _int_col(rng.integers(1, 11, n_items)),  # i_category_id
            _int_col(rng.integers(1, 30, n_items)),  # i_class_id
        ],
        ["i_item_sk", "i_item_id", "i_category_id", "i_class_id"],
    )
    customer_demographics = Table(
        [
            _int_col(np.arange(n_cdemo)),
            _int_col(rng.integers(0, 2, n_cdemo)),  # cd_gender
            _int_col(rng.integers(0, 5, n_cdemo)),  # cd_marital_status
            _int_col(rng.integers(0, 7, n_cdemo)),  # cd_education_status
        ],
        ["cd_demo_sk", "cd_gender", "cd_marital_status", "cd_education_status"],
    )
    promotion = Table(
        [
            _int_col(np.arange(n_promo)),
            _int_col(rng.integers(0, 2, n_promo)),  # p_channel_email
            _int_col(rng.integers(0, 2, n_promo)),  # p_channel_event
        ],
        ["p_promo_sk", "p_channel_email", "p_channel_event"],
    )
    catalog_sales = Table(
        [
            _int_col(rng.integers(0, n_dates, num_sales)),  # cs_sold_date_sk
            _int_col(rng.integers(0, n_items, num_sales)),  # cs_item_sk
            _int_col(rng.integers(0, n_cdemo, num_sales)),  # cs_bill_cdemo_sk
            _int_col(rng.integers(0, n_promo, num_sales)),  # cs_promo_sk
            _int_col(rng.integers(1, 100, num_sales)),  # cs_quantity
            _f64_col(rng.uniform(1, 200, num_sales).round(2)),  # cs_list_price
            _f64_col(rng.uniform(0, 50, num_sales).round(2)),  # cs_coupon_amt
            _f64_col(rng.uniform(1, 150, num_sales).round(2)),  # cs_sales_price
            _f64_col(rng.uniform(1, 1000, num_sales).round(2)),  # cs_ext_sales_price
        ],
        [
            "cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk", "cs_promo_sk",
            "cs_quantity", "cs_list_price", "cs_coupon_amt", "cs_sales_price",
            "cs_ext_sales_price",
        ],
    )
    return {"catalog_sales": catalog_sales, "date_dim": date_dim, "item": item,
            "customer_demographics": customer_demographics, "promotion": promotion}


def gen_channels(num_rows: int, seed: int = 29) -> Dict[str, Table]:
    """Three sales channels sharing one customer population — the
    INTERSECT/EXCEPT (q38/q87) and EXISTS/NOT-EXISTS (q69) families."""
    rng = np.random.default_rng(seed)
    n_dates, n_cust, n_cdemo, n_addr = 365 * 3, 1200, 120, 300
    date_dim = Table(
        [
            _int_col(np.arange(n_dates)),
            _int_col(1998 + np.arange(n_dates) // 365),
            _int_col(1 + (np.arange(n_dates) % 365) // 31),
        ],
        ["d_date_sk", "d_year", "d_moy"],
    )
    customer = Table(
        [
            _int_col(np.arange(n_cust)),
            _int_col(rng.permutation(n_cust)),  # c_customer_id
            _int_col(rng.integers(0, n_cdemo, n_cust)),  # c_current_cdemo_sk
            _int_col(rng.integers(0, n_addr, n_cust)),  # c_current_addr_sk
        ],
        ["c_customer_sk", "c_customer_id", "c_current_cdemo_sk", "c_current_addr_sk"],
    )
    customer_address = Table(
        [_int_col(np.arange(n_addr)), _int_col(rng.integers(0, 10, n_addr))],
        ["ca_address_sk", "ca_state"],
    )
    customer_demographics = Table(
        [
            _int_col(np.arange(n_cdemo)),
            _int_col(rng.integers(0, 2, n_cdemo)),  # cd_gender
            _int_col(rng.integers(0, 5, n_cdemo)),  # cd_marital_status
            _int_col(rng.integers(0, 7, n_cdemo)),  # cd_education_status
        ],
        ["cd_demo_sk", "cd_gender", "cd_marital_status", "cd_education_status"],
    )

    def fact(cust_col: str, date_col: str, n: int) -> Table:
        return Table(
            [_int_col(rng.integers(0, n_cust, n)), _int_col(rng.integers(0, n_dates, n))],
            [cust_col, date_col],
        )

    tables = {
        "date_dim": date_dim,
        "customer": customer,
        "customer_address": customer_address,
        "customer_demographics": customer_demographics,
        "store_sales": fact("ss_customer_sk", "ss_sold_date_sk", num_rows),
        "web_sales": fact("ws_bill_customer_sk", "ws_sold_date_sk", max(num_rows // 2, 1)),
        "catalog_sales": fact("cs_ship_customer_sk", "cs_sold_date_sk", max(num_rows // 2, 1)),
    }
    # srjt-cbo (ISSUE 19) extension — the q35 dependent-count lane is
    # drawn AFTER every pre-existing draw (the gen_store_wide append
    # pattern), so the original columns' random sequences are untouched.
    tables["customer_demographics"] = Table(
        list(customer_demographics.columns)
        + [_int_col(rng.integers(0, 10, n_cdemo))],  # cd_dep_count
        list(customer_demographics.names) + ["cd_dep_count"],
    )
    return tables


# ---------------------------------------------------------------------------
# plan builders + runners
# ---------------------------------------------------------------------------


def _run(plan: P.Node, tables: Dict[str, Table], name: str) -> Table:
    return P.compile_ir(plan, tables, name=name)()


def q1_plan(year: int = 1998, state: int = 3) -> P.Node:
    """TPC-DS q1 — the flagship decorrelation shape. SQL:

        WITH customer_total_return AS (
          SELECT sr_customer_sk, sr_store_sk,
                 sum(sr_return_amt) ctr_total_return
          FROM store_returns, date_dim
          WHERE sr_returned_date_sk = d_date_sk AND d_year = :yr
          GROUP BY sr_customer_sk, sr_store_sk)
        SELECT c_customer_id
        FROM customer_total_return ctr1, store, customer
        WHERE ctr1.ctr_total_return >
              (SELECT avg(ctr_total_return) * 1.2
               FROM customer_total_return ctr2
               WHERE ctr1.sr_store_sk = ctr2.sr_store_sk)
          AND s_store_sk = ctr1.sr_store_sk AND s_state = :state
          AND ctr1.sr_customer_sk = c_customer_sk
        ORDER BY c_customer_id LIMIT 100

    The CTE is ONE shared node used twice (the compiler evaluates it
    once); the correlated average decorrelates to agg + join."""
    ctr = P.Aggregate(
        P.Join(
            P.Scan("store_returns"),
            P.Filter(P.Scan("date_dim"), P.pcol("d_year") == P.plit(year)),
            on=(("sr_returned_date_sk", "d_date_sk"),), bounded=True,
        ),
        keys=("sr_customer_sk", "sr_store_sk"),
        aggs=(P.AggSpec("sr_return_amt", "sum", "ctr_total_return"),),
    )
    x = P.CorrelatedAggFilter(
        ctr, ctr, on=("sr_store_sk", "sr_store_sk"),
        agg=P.AggSpec("ctr_total_return", "mean", "ctr_avg"),
        predicate=P.pcol("ctr_total_return") > P.pcol("ctr_avg") * P.plit(1.2),
    )
    x = P.Join(x, P.Filter(P.Scan("store"), P.pcol("s_state") == P.plit(state)),
               on=(("sr_store_sk", "s_store_sk"),))
    x = P.Join(x, P.Scan("customer"), on=(("sr_customer_sk", "c_customer_sk"),))
    x = P.Project(x, (("c_customer_id", P.pcol("c_customer_id")),))
    return P.Limit(P.Sort(x, (("c_customer_id", True),)), 100)


def q1(tables: Dict[str, Table], year: int = 1998, state: int = 3) -> Table:
    return _run(q1_plan(year, state), tables, "q1")


def q92_plan(manufact: int = 35, lo: int = 200, hi: int = 290) -> P.Node:
    """TPC-DS q92 (excess discount amount) — decorrelate avg * 1.3. SQL:

        SELECT sum(ws_ext_discount_amt)
        FROM web_sales, item, date_dim
        WHERE i_manufact_id = :m AND i_item_sk = ws_item_sk
          AND d_date_sk = ws_sold_date_sk AND d_date BETWEEN :lo AND :hi
          AND ws_ext_discount_amt >
              (SELECT 1.3 * avg(ws_ext_discount_amt)
               FROM web_sales, date_dim
               WHERE ws_item_sk = i_item_sk
                 AND d_date_sk = ws_sold_date_sk
                 AND d_date BETWEEN :lo AND :hi)

    The date-filtered web_sales is one shared node (fact side AND
    subquery side); the decorrelated per-item average joins back as a
    MATERIALIZED build inside the fused final aggregation."""
    dated = P.Join(
        P.Scan("web_sales"),
        P.Filter(P.Scan("date_dim"),
                 (P.pcol("d_date_sk") >= P.plit(lo))
                 & (P.pcol("d_date_sk") <= P.plit(hi))),
        on=(("ws_sold_date_sk", "d_date_sk"),), bounded=True,
    )
    main = P.Join(
        dated,
        P.Filter(P.Scan("item"), P.pcol("i_manufact_id") == P.plit(manufact)),
        on=(("ws_item_sk", "i_item_sk"),), bounded=True,
    )
    x = P.CorrelatedAggFilter(
        main, dated, on=("ws_item_sk", "ws_item_sk"),
        agg=P.AggSpec("ws_ext_discount_amt", "mean", "avg_disc"),
        predicate=P.pcol("ws_ext_discount_amt") > P.plit(1.3) * P.pcol("avg_disc"),
    )
    return P.Aggregate(x, keys=(),
                       aggs=(P.AggSpec("ws_ext_discount_amt", "sum", "excess"),))


def q92(tables, manufact: int = 35, lo: int = 200, hi: int = 290) -> Table:
    return _run(q92_plan(manufact, lo, hi), tables, "q92")


def q26_plan(gender: int = 1, marital: int = 2, education: int = 3,
             year: int = 2000) -> P.Node:
    """TPC-DS q26 — q7's catalog-channel twin: 4-way star with exact
    FLOAT64/int AVG aggregates, fully fused. SQL:

        SELECT i_item_id, avg(cs_quantity), avg(cs_list_price),
               avg(cs_coupon_amt), avg(cs_sales_price)
        FROM catalog_sales, customer_demographics, date_dim, item, promotion
        WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
          AND cs_bill_cdemo_sk = cd_demo_sk AND cs_promo_sk = p_promo_sk
          AND cd_gender = :g AND cd_marital_status = :m
          AND cd_education_status = :e
          AND (p_channel_email = 'N' OR p_channel_event = 'N')
          AND d_year = :y
        GROUP BY i_item_id ORDER BY i_item_id
    """
    x = P.Scan("catalog_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"), P.pcol("d_year") == P.plit(year)),
               on=(("cs_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(
        x,
        P.Filter(P.Scan("customer_demographics"),
                 (P.pcol("cd_gender") == P.plit(gender))
                 & (P.pcol("cd_marital_status") == P.plit(marital))
                 & (P.pcol("cd_education_status") == P.plit(education))),
        on=(("cs_bill_cdemo_sk", "cd_demo_sk"),), bounded=True,
    )
    x = P.Join(
        x,
        P.Filter(P.Scan("promotion"),
                 (P.pcol("p_channel_email") == P.plit(0))
                 | (P.pcol("p_channel_event") == P.plit(0))),
        on=(("cs_promo_sk", "p_promo_sk"),), bounded=True,
    )
    x = P.Join(x, P.Scan("item"), on=(("cs_item_sk", "i_item_sk"),), bounded=True)
    agg = P.Aggregate(
        x, keys=("i_item_id",),
        aggs=(
            P.AggSpec("cs_quantity", "mean", "agg1"),
            P.AggSpec("cs_list_price", "mean", "agg2"),
            P.AggSpec("cs_coupon_amt", "mean", "agg3"),
            P.AggSpec("cs_sales_price", "mean", "agg4"),
        ),
    )
    return P.Sort(agg, (("i_item_id", True),))


def q26(tables, gender: int = 1, marital: int = 2, education: int = 3,
        year: int = 2000) -> Table:
    return _run(q26_plan(gender, marital, education, year), tables, "q26")


def q20_plan(cats=(2, 5, 8), lo: int = 700, hi: int = 730) -> P.Node:
    """TPC-DS q20 — the partition-sum-ratio reporting family (q12/q20/
    q98) on the catalog channel: class revenue plus each class's share
    of its category, via the window tier over a fused aggregation. SQL:

        SELECT i_category_id, i_class_id, sum(cs_ext_sales_price) itemrevenue,
               sum(cs_ext_sales_price) * 100 /
                 sum(sum(cs_ext_sales_price)) OVER (PARTITION BY i_category_id)
        FROM catalog_sales, item, date_dim
        WHERE cs_item_sk = i_item_sk AND i_category_id IN (:a,:b,:c)
          AND cs_sold_date_sk = d_date_sk AND d_date BETWEEN :lo AND :hi
        GROUP BY i_category_id, i_class_id
        ORDER BY i_category_id, revenueratio, i_class_id
    """
    in_list = None
    for c in cats:
        e = P.pcol("i_category_id") == P.plit(c)
        in_list = e if in_list is None else (in_list | e)
    x = P.Scan("catalog_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"),
                           (P.pcol("d_date_sk") >= P.plit(lo))
                           & (P.pcol("d_date_sk") <= P.plit(hi))),
               on=(("cs_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(x, P.Filter(P.Scan("item"), in_list),
               on=(("cs_item_sk", "i_item_sk"),), bounded=True)
    agg = P.Aggregate(x, keys=("i_category_id", "i_class_id"),
                      aggs=(P.AggSpec("cs_ext_sales_price", "sum", "itemrevenue"),))
    w = P.Window(agg, partition_by=("i_category_id",), order_by=(),
                 aggs=(("itemrevenue", "sum", "cat_total"),))
    proj = P.Project(w, (
        ("i_category_id", P.pcol("i_category_id")),
        ("i_class_id", P.pcol("i_class_id")),
        ("itemrevenue", P.pcol("itemrevenue")),
        ("revenueratio",
         (P.pcol("itemrevenue") * P.plit(100.0)) / P.pcol("cat_total")),
    ))
    return P.Sort(proj, (("i_category_id", True), ("revenueratio", True),
                         ("i_class_id", True)))


def q20(tables, cats=(2, 5, 8), lo: int = 700, hi: int = 730) -> Table:
    return _run(q20_plan(cats, lo, hi), tables, "q20")


def q27_plan(gender: int = 1, marital: int = 2, education: int = 3,
             year: int = 2000, states=(1, 4, 7)) -> P.Node:
    """TPC-DS q27 — ROLLUP over the store star: the optimizer expands
    ``rollup(i_item_id, s_state)`` into a UnionAll of three fused
    group-bys with null-filled rolled keys. SQL:

        SELECT i_item_id, s_state, grouping(s_state),
               avg(ss_quantity), avg(ss_list_price),
               avg(ss_coupon_amt), avg(ss_sales_price)
        FROM store_sales, customer_demographics, date_dim, store, item
        WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
          AND ss_store_sk = s_store_sk AND ss_cdemo_sk = cd_demo_sk
          AND cd_gender = :g AND cd_marital_status = :m
          AND cd_education_status = :e AND d_year = :y
          AND s_state IN (:states)
        GROUP BY ROLLUP(i_item_id, s_state)
    """
    in_states = None
    for s in states:
        e = P.pcol("s_state") == P.plit(s)
        in_states = e if in_states is None else (in_states | e)
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"), P.pcol("d_year") == P.plit(year)),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(
        x,
        P.Filter(P.Scan("customer_demographics"),
                 (P.pcol("cd_gender") == P.plit(gender))
                 & (P.pcol("cd_marital_status") == P.plit(marital))
                 & (P.pcol("cd_education_status") == P.plit(education))),
        on=(("ss_cdemo_sk", "cd_demo_sk"),), bounded=True,
    )
    x = P.Join(x, P.Filter(P.Scan("store"), in_states),
               on=(("ss_store_sk", "s_store_sk"),), bounded=True)
    x = P.Join(x, P.Scan("item"), on=(("ss_item_sk", "i_item_sk"),), bounded=True)
    return P.Aggregate(
        x, keys=("i_item_id", "s_state"),
        aggs=(
            P.AggSpec("ss_quantity", "mean", "agg1"),
            P.AggSpec("ss_list_price", "mean", "agg2"),
            P.AggSpec("ss_coupon_amt", "mean", "agg3"),
            P.AggSpec("ss_sales_price", "mean", "agg4"),
        ),
        grouping_sets=P.rollup("i_item_id", "s_state"),
    )


def q27(tables, gender: int = 1, marital: int = 2, education: int = 3,
        year: int = 2000, states=(1, 4, 7)) -> Table:
    return _run(q27_plan(gender, marital, education, year, states), tables, "q27")


def q43_plan(year: int = 2000) -> P.Node:
    """TPC-DS q43 — the day-name CASE pivot: per-store weekly sales
    matrix via seven CASE-WHEN projections summed in ONE fused program.
    SQL shape:

        SELECT s_store_sk,
               sum(CASE WHEN d_dow = 0 THEN ss_sales_price END) sun_sales,
               ... (mon..sat) ...
        FROM date_dim, store_sales
        WHERE d_date_sk = ss_sold_date_sk AND d_year = :y
        GROUP BY s_store_sk(-> ss_store_sk code) ORDER BY s_store_sk
    """
    x = P.Join(
        P.Scan("store_sales"),
        P.Filter(P.Scan("date_dim"), P.pcol("d_year") == P.plit(year)),
        on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True,
    )
    days = ("sun", "mon", "tue", "wed", "thu", "fri", "sat")
    exprs = [("ss_store_sk", P.pcol("ss_store_sk"))]
    for i, day in enumerate(days):
        exprs.append((
            f"{day}_sales",
            P.pwhen(P.pcol("d_dow") == P.plit(i), P.pcol("ss_sales_price"),
                    P.plit(None, dt.FLOAT64)),
        ))
    proj = P.Project(x, tuple(exprs))
    agg = P.Aggregate(
        proj, keys=("ss_store_sk",),
        aggs=tuple(P.AggSpec(f"{d}_sales", "sum", f"{d}_sales_sum") for d in days),
    )
    return P.Sort(agg, (("ss_store_sk", True),))


def q43(tables, year: int = 2000) -> Table:
    return _run(q43_plan(year), tables, "q43")


def q88_plan(deps=(2, 7), hours=(8, 9, 10, 11)) -> P.Node:
    """TPC-DS q88 — eight half-hour time-band store traffic counts, one
    fused global-count star per band, UNION ALLed into a (band, cnt)
    report. SQL shape (per band):

        SELECT count(*) FROM store_sales, household_demographics, time_dim
        WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
          AND t_hour = :h AND t_minute [< | >=] 30
          AND (hd_dep_count = :d1 OR hd_dep_count = :d2)
    """
    hd_filter = ((P.pcol("hd_dep_count") == P.plit(deps[0]))
                 | (P.pcol("hd_dep_count") == P.plit(deps[1])))
    branches = []
    band = 0
    for h in hours:
        for half in (0, 1):
            tf = P.pcol("t_hour") == P.plit(h)
            tf = tf & ((P.pcol("t_minute") < P.plit(30)) if half == 0
                       else (P.pcol("t_minute") >= P.plit(30)))
            x = P.Scan("store_sales")
            x = P.Join(x, P.Filter(P.Scan("time_dim"), tf),
                       on=(("ss_sold_time_sk", "t_time_sk"),), bounded=True)
            x = P.Join(x, P.Filter(P.Scan("household_demographics"), hd_filter),
                       on=(("ss_hdemo_sk", "hd_demo_sk"),), bounded=True)
            agg = P.Aggregate(x, keys=(), aggs=(P.AggSpec(None, "count_all", "cnt"),))
            branches.append(P.Project(agg, (
                ("band", P.plit(np.int32(band))), ("cnt", P.pcol("cnt")),
            )))
            band += 1
    return P.UnionAll(tuple(branches))


def q88(tables, deps=(2, 7), hours=(8, 9, 10, 11)) -> Table:
    return _run(q88_plan(deps, hours), tables, "q88")


def q96_plan(hour: int = 20, dep: int = 5) -> P.Node:
    """TPC-DS q96 — one half-hour demographic count (q88's single-band
    sibling), a fused global COUNT(*) star. SQL:

        SELECT count(*) FROM store_sales, household_demographics, time_dim
        WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
          AND t_hour = :h AND t_minute >= 30 AND hd_dep_count = :d
    """
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("time_dim"),
                           (P.pcol("t_hour") == P.plit(hour))
                           & (P.pcol("t_minute") >= P.plit(30))),
               on=(("ss_sold_time_sk", "t_time_sk"),), bounded=True)
    x = P.Join(x, P.Filter(P.Scan("household_demographics"),
                           P.pcol("hd_dep_count") == P.plit(dep)),
               on=(("ss_hdemo_sk", "hd_demo_sk"),), bounded=True)
    return P.Aggregate(x, keys=(), aggs=(P.AggSpec(None, "count_all", "cnt"),))


def q96(tables, hour: int = 20, dep: int = 5) -> Table:
    return _run(q96_plan(hour, dep), tables, "q96")


def _channel_customers(fact: str, cust_col: str, year: int, moy_lo: int,
                       moy_hi: int) -> P.Node:
    """Customer ids active on one channel inside a month window — the
    shared branch of the q38/q87 set-op chains."""
    x = P.Join(
        P.Scan(fact),
        P.Filter(P.Scan("date_dim"),
                 (P.pcol("d_year") == P.plit(year))
                 & (P.pcol("d_moy") >= P.plit(moy_lo))
                 & (P.pcol("d_moy") <= P.plit(moy_hi))),
        on=((f"{cust_col[:2]}_sold_date_sk", "d_date_sk"),), bounded=True,
    )
    x = P.Join(x, P.Scan("customer"), on=((cust_col, "c_customer_sk"),),
               bounded=True)
    return P.Project(x, (("c_customer_id", P.pcol("c_customer_id")),))


def q38_plan(year: int = 1999, moy_lo: int = 1, moy_hi: int = 7) -> P.Node:
    """TPC-DS q38 — INTERSECT chain: customers active on ALL THREE
    channels in the window; the optimizer lowers both INTERSECTs to
    semi-joins on deduplicated keys. SQL shape:

        SELECT count(*) FROM (
          SELECT c_customer_id FROM store_sales, date_dim, customer WHERE ...
          INTERSECT SELECT ... FROM catalog_sales ...
          INTERSECT SELECT ... FROM web_sales ...) hot_cust
    """
    s = _channel_customers("store_sales", "ss_customer_sk", year, moy_lo, moy_hi)
    c = _channel_customers("catalog_sales", "cs_ship_customer_sk", year, moy_lo, moy_hi)
    w = _channel_customers("web_sales", "ws_bill_customer_sk", year, moy_lo, moy_hi)
    chain = P.SetOp(P.SetOp(s, c, "intersect"), w, "intersect")
    return P.Aggregate(chain, keys=(), aggs=(P.AggSpec(None, "count_all", "cnt"),))


def q38(tables, year: int = 1999, moy_lo: int = 1, moy_hi: int = 7) -> Table:
    return _run(q38_plan(year, moy_lo, moy_hi), tables, "q38")


def q87_plan(year: int = 1999, moy_lo: int = 1, moy_hi: int = 7) -> P.Node:
    """TPC-DS q87 — the EXCEPT twin of q38: store customers with NO
    catalog and NO web activity in the window (anti-joins on deduped
    keys)."""
    s = _channel_customers("store_sales", "ss_customer_sk", year, moy_lo, moy_hi)
    c = _channel_customers("catalog_sales", "cs_ship_customer_sk", year, moy_lo, moy_hi)
    w = _channel_customers("web_sales", "ws_bill_customer_sk", year, moy_lo, moy_hi)
    chain = P.SetOp(P.SetOp(s, c, "except"), w, "except")
    return P.Aggregate(chain, keys=(), aggs=(P.AggSpec(None, "count_all", "cnt"),))


def q87(tables, year: int = 1999, moy_lo: int = 1, moy_hi: int = 7) -> Table:
    return _run(q87_plan(year, moy_lo, moy_hi), tables, "q87")


def q69_plan(states=(2, 5, 8), year: int = 1999, moy_lo: int = 1,
             moy_hi: int = 3) -> P.Node:
    """TPC-DS q69 — demographic counts of customers with store activity
    but NO web/catalog activity in the window: one EXISTS plus two NOT
    EXISTS, all lowered to semi/anti joins that FUSE into the one
    compiled program over the customer table (the subquery sides
    materialize as build tables). SQL shape:

        SELECT cd_gender, cd_marital_status, cd_education_status, count(*)
        FROM customer c, customer_address ca, customer_demographics
        WHERE c_current_addr_sk = ca_address_sk AND ca_state IN (:states)
          AND cd_demo_sk = c_current_cdemo_sk
          AND EXISTS (SELECT * FROM store_sales, date_dim WHERE ...)
          AND NOT EXISTS (SELECT * FROM web_sales, date_dim WHERE ...)
          AND NOT EXISTS (SELECT * FROM catalog_sales, date_dim WHERE ...)
        GROUP BY cd_gender, cd_marital_status, cd_education_status
        ORDER BY cd_gender, cd_marital_status, cd_education_status
    """
    in_states = None
    for s in states:
        e = P.pcol("ca_state") == P.plit(s)
        in_states = e if in_states is None else (in_states | e)
    dates = P.Filter(P.Scan("date_dim"),
                     (P.pcol("d_year") == P.plit(year))
                     & (P.pcol("d_moy") >= P.plit(moy_lo))
                     & (P.pcol("d_moy") <= P.plit(moy_hi)))

    def active(fact: str, cust_col: str) -> P.Node:
        prefix = cust_col.split("_")[0]
        return P.Join(P.Scan(fact), dates,
                      on=((f"{prefix}_sold_date_sk", "d_date_sk"),), bounded=True)

    x = P.Join(P.Scan("customer"),
               P.Filter(P.Scan("customer_address"), in_states),
               on=(("c_current_addr_sk", "ca_address_sk"),), bounded=True)
    x = P.Join(x, P.Scan("customer_demographics"),
               on=(("c_current_cdemo_sk", "cd_demo_sk"),), bounded=True)
    x = P.Exists(x, active("store_sales", "ss_customer_sk"),
                 on=(("c_customer_sk", "ss_customer_sk"),))
    x = P.Exists(x, active("web_sales", "ws_bill_customer_sk"),
                 on=(("c_customer_sk", "ws_bill_customer_sk"),), negated=True)
    x = P.Exists(x, active("catalog_sales", "cs_ship_customer_sk"),
                 on=(("c_customer_sk", "cs_ship_customer_sk"),), negated=True)
    agg = P.Aggregate(
        x, keys=("cd_gender", "cd_marital_status", "cd_education_status"),
        aggs=(P.AggSpec(None, "count_all", "cnt"),),
    )
    return P.Sort(agg, (("cd_gender", True), ("cd_marital_status", True),
                        ("cd_education_status", True)))


def q69(tables, states=(2, 5, 8), year: int = 1999, moy_lo: int = 1,
        moy_hi: int = 3) -> Table:
    return _run(q69_plan(states, year, moy_lo, moy_hi), tables, "q69")


def q73_plan(year: int = 2000, buys=(1, 4), lo: int = 1, hi: int = 2) -> P.Node:
    """TPC-DS q73 — the HAVING count band: per-(ticket, customer) item
    counts filtered to a band, joined back to customer. The inner
    aggregation fuses; HAVING lowers to a post-aggregate Filter; the
    join-back runs on the (small) aggregate output. SQL shape:

        SELECT c_customer_id, cnt FROM (
          SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
          FROM store_sales, date_dim, household_demographics
          WHERE ss_sold_date_sk = d_date_sk AND ss_hdemo_sk = hd_demo_sk
            AND d_year = :y AND hd_buy_potential IN (:b1, :b2)
          GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
        WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN :lo AND :hi
        ORDER BY cnt DESC, c_customer_id
    """
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"), P.pcol("d_year") == P.plit(year)),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(
        x,
        P.Filter(P.Scan("household_demographics"),
                 (P.pcol("hd_buy_potential") == P.plit(buys[0]))
                 | (P.pcol("hd_buy_potential") == P.plit(buys[1]))),
        on=(("ss_hdemo_sk", "hd_demo_sk"),), bounded=True,
    )
    agg = P.Aggregate(x, keys=("ss_ticket_number", "ss_customer_sk"),
                      aggs=(P.AggSpec(None, "count_all", "cnt"),))
    hv = P.Having(agg, (P.pcol("cnt") >= P.plit(lo)) & (P.pcol("cnt") <= P.plit(hi)))
    j = P.Join(hv, P.Scan("customer"), on=(("ss_customer_sk", "c_customer_sk"),))
    proj = P.Project(j, (("c_customer_id", P.pcol("c_customer_id")),
                         ("cnt", P.pcol("cnt"))))
    return P.Sort(proj, (("cnt", False), ("c_customer_id", True)))


def q73(tables, year: int = 2000, buys=(1, 4), lo: int = 1, hi: int = 2) -> Table:
    return _run(q73_plan(year, buys, lo, hi), tables, "q73")


def q13_plan(year: int = 2000) -> P.Node:
    """TPC-DS q13 — the OR'ed demographic/price band star over six
    joined dimensions, global exact averages; the whole chain (six
    inner joins + the cross-dimension band filter + four aggregates)
    fuses into ONE compiled program under the new srjt-plancheck
    verifier. SQL shape:

        SELECT avg(ss_quantity), avg(ss_list_price), avg(ss_coupon_amt),
               sum(ss_sales_price)
        FROM store_sales, store, customer_demographics,
             household_demographics, customer, customer_address, date_dim
        WHERE d_year = :y AND ss_store_sk = s_store_sk
          AND ss_cdemo_sk = cd_demo_sk AND ss_hdemo_sk = hd_demo_sk
          AND ss_customer_sk = c_customer_sk
          AND c_current_addr_sk = ca_address_sk
          AND ((cd_marital_status = 'M' AND cd_education_status = ... AND
                ss_sales_price BETWEEN .. AND hd_dep_count = ..) OR (...))
          AND (ca_state IN (...) ...)

    Dictionary codes stand in for the string bands (ca_zip5 for the
    address band), as everywhere in this tier."""
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"), P.pcol("d_year") == P.plit(year)),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(x, P.Scan("store"), on=(("ss_store_sk", "s_store_sk"),),
               bounded=True)
    x = P.Join(x, P.Scan("customer_demographics"),
               on=(("ss_cdemo_sk", "cd_demo_sk"),), bounded=True)
    x = P.Join(x, P.Scan("household_demographics"),
               on=(("ss_hdemo_sk", "hd_demo_sk"),), bounded=True)
    x = P.Join(x, P.Scan("customer"), on=(("ss_customer_sk", "c_customer_sk"),),
               bounded=True)
    x = P.Join(x, P.Scan("customer_address"),
               on=(("c_current_addr_sk", "ca_address_sk"),), bounded=True)
    band1 = ((P.pcol("cd_marital_status") <= P.plit(2))
             & (P.pcol("cd_education_status") >= P.plit(3))
             & (P.pcol("ss_sales_price") >= P.plit(50.0))
             & (P.pcol("hd_dep_count") <= P.plit(5)))
    band2 = ((P.pcol("cd_marital_status") >= P.plit(3))
             & (P.pcol("cd_education_status") <= P.plit(2))
             & (P.pcol("ss_sales_price") <= P.plit(100.0))
             & (P.pcol("hd_dep_count") >= P.plit(4)))
    zips = (P.pcol("ca_zip5") < P.plit(120)) | (P.pcol("ca_zip5") >= P.plit(210))
    x = P.Filter(x, (band1 | band2) & zips)
    return P.Aggregate(
        x, keys=(),
        aggs=(
            P.AggSpec("ss_quantity", "mean", "avg_qty"),
            P.AggSpec("ss_list_price", "mean", "avg_list"),
            P.AggSpec("ss_coupon_amt", "mean", "avg_coupon"),
            P.AggSpec("ss_sales_price", "sum", "sum_sales"),
        ),
    )


def q13(tables: Dict[str, Table], year: int = 2000) -> Table:
    return _run(q13_plan(year), tables, "q13")


def q48_plan(year: int = 2000) -> P.Node:
    """TPC-DS q48 — q13's global-sum sibling: demographic/price bands
    OR'ed with address bands over the store star, one fused global
    SUM(ss_quantity). SQL shape:

        SELECT sum(ss_quantity)
        FROM store_sales, store, customer_demographics, customer,
             customer_address, date_dim
        WHERE d_year = :y AND ss_store_sk = s_store_sk AND ...
          AND ((cd_marital_status = .. AND cd_education_status = .. AND
                ss_sales_price BETWEEN ..) OR (...))
          AND ((ca_state IN (..) AND ss_net_profit BETWEEN ..) OR (...))
    """
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"), P.pcol("d_year") == P.plit(year)),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(x, P.Scan("store"), on=(("ss_store_sk", "s_store_sk"),),
               bounded=True)
    x = P.Join(x, P.Scan("customer_demographics"),
               on=(("ss_cdemo_sk", "cd_demo_sk"),), bounded=True)
    x = P.Join(x, P.Scan("customer"), on=(("ss_customer_sk", "c_customer_sk"),),
               bounded=True)
    x = P.Join(x, P.Scan("customer_address"),
               on=(("c_current_addr_sk", "ca_address_sk"),), bounded=True)
    demo = (((P.pcol("cd_marital_status") == P.plit(2))
             & (P.pcol("cd_education_status") == P.plit(3))
             & (P.pcol("ss_sales_price") >= P.plit(50.0))
             & (P.pcol("ss_sales_price") <= P.plit(150.0)))
            | ((P.pcol("cd_marital_status") == P.plit(1))
               & (P.pcol("cd_education_status") == P.plit(4))
               & (P.pcol("ss_sales_price") <= P.plit(100.0))))
    addr = ((P.pcol("ca_zip5") < P.plit(100))
            | ((P.pcol("ca_zip5") >= P.plit(150)) & (P.pcol("ca_zip5") < P.plit(250))))
    x = P.Filter(x, demo & addr)
    return P.Aggregate(x, keys=(),
                       aggs=(P.AggSpec("ss_quantity", "sum", "qty_sum"),))


def q48(tables: Dict[str, Table], year: int = 2000) -> Table:
    return _run(q48_plan(year), tables, "q48")


def q65_plan(lo: int = 400, hi: int = 1100, frac: float = 0.5) -> P.Node:
    """TPC-DS q65 — low-revenue items per store: per-(store, item)
    revenue compared against a fraction of the per-store AVERAGE
    revenue — the correlated scalar subquery decorrelates exactly like
    q1, and the inner (store, item) revenue aggregate FUSES (both keys
    dense INT32 domains); the comparison + item join-back run on the
    small aggregate output. SQL:

        SELECT s_store_sk, i_item_id, revenue FROM store, item,
          (SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
           FROM store_sales, date_dim
           WHERE ss_sold_date_sk = d_date_sk AND d_date_sk BETWEEN :lo AND :hi
           GROUP BY ss_store_sk, ss_item_sk) sa
        WHERE sa.revenue <= :frac *
              (SELECT avg(revenue) FROM sa sb
               WHERE sb.ss_store_sk = sa.ss_store_sk)
          AND ss_item_sk = i_item_sk
        ORDER BY s_store_sk, i_item_id
    """
    sa = P.Aggregate(
        P.Join(
            P.Scan("store_sales"),
            P.Filter(P.Scan("date_dim"),
                     (P.pcol("d_date_sk") >= P.plit(lo))
                     & (P.pcol("d_date_sk") <= P.plit(hi))),
            on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True,
        ),
        keys=("ss_store_sk", "ss_item_sk"),
        aggs=(P.AggSpec("ss_sales_price", "sum", "revenue"),),
    )
    x = P.CorrelatedAggFilter(
        sa, sa, on=("ss_store_sk", "ss_store_sk"),
        agg=P.AggSpec("revenue", "mean", "ave"),
        predicate=P.pcol("revenue") <= P.plit(frac) * P.pcol("ave"),
    )
    x = P.Join(x, P.Scan("item"), on=(("ss_item_sk", "i_item_sk"),))
    x = P.Project(x, (("ss_store_sk", P.pcol("ss_store_sk")),
                      ("i_item_id", P.pcol("i_item_id")),
                      ("revenue", P.pcol("revenue"))))
    return P.Sort(x, (("ss_store_sk", True), ("i_item_id", True)))


def q65(tables: Dict[str, Table], lo: int = 400, hi: int = 1100,
        frac: float = 0.5) -> Table:
    return _run(q65_plan(lo, hi, frac), tables, "q65")


# ---------------------------------------------------------------------------
# srjt-cbo (ISSUE 19) mass-green campaign — ten more lowers go green
# through the compiler; the multi-join chains among them (q8/q15/q30/
# q34/q35) double as checked-in exercise for the cost-based join
# enumeration (cbo_reorder_joins / cbo_build_side / cbo_join_strategy).
# ---------------------------------------------------------------------------


def q9_plan(thresholds=(2100, 2100, 2100, 2100, 1800)) -> P.Node:
    """TPC-DS q9 — the bucketed CASE report: five quantity bands over
    store_sales alone; each band's output column picks one of two
    global averages depending on the band's row count. SQL shape (per
    bucket)::

        SELECT CASE WHEN (SELECT count(*) FROM store_sales
                          WHERE ss_quantity BETWEEN :lo AND :hi) > :t
                    THEN (SELECT avg(ss_ext_sales_price) ...)
                    ELSE (SELECT avg(ss_coupon_amt) ...) END bucket_n

    Each bucket is one fused global aggregate; the CASE is a projection
    over the aggregate's (cnt, avg, avg) row; buckets UNION ALL into a
    (bucket, val) report."""
    branches = []
    for i, th in enumerate(thresholds):
        lo, hi = 1 + 20 * i, 20 + 20 * i
        band = (P.pcol("ss_quantity") >= P.plit(lo)) & (P.pcol("ss_quantity") <= P.plit(hi))
        agg = P.Aggregate(
            P.Filter(P.Scan("store_sales"), band), keys=(),
            aggs=(
                P.AggSpec(None, "count_all", "cnt"),
                P.AggSpec("ss_ext_sales_price", "mean", "avg_ext"),
                P.AggSpec("ss_coupon_amt", "mean", "avg_coup"),
            ),
        )
        branches.append(P.Project(agg, (
            ("bucket", P.plit(np.int32(i))),
            ("val", P.pwhen(P.pcol("cnt") > P.plit(th),
                            P.pcol("avg_ext"), P.pcol("avg_coup"))),
        )))
    return P.UnionAll(tuple(branches))


def q9(tables: Dict[str, Table], thresholds=(2100, 2100, 2100, 2100, 1800)) -> Table:
    return _run(q9_plan(thresholds), tables, "q9")


def q28_plan() -> P.Node:
    """TPC-DS q28 — six band aggregates over store_sales alone:
    per quantity band (with OR'ed list-price/coupon side bands),
    avg / count / count(DISTINCT) of ss_list_price, UNION ALLed. SQL
    shape (per band)::

        SELECT avg(ss_list_price), count(ss_list_price),
               count(DISTINCT ss_list_price)
        FROM store_sales
        WHERE ss_quantity BETWEEN :lo AND :hi
          AND (ss_list_price BETWEEN :a AND :b
               OR ss_coupon_amt BETWEEN :c AND :d)
    """
    branches = []
    for i in range(6):
        qlo, qhi = 1 + 16 * i, 16 + 16 * i
        pred = ((P.pcol("ss_quantity") >= P.plit(qlo))
                & (P.pcol("ss_quantity") <= P.plit(qhi))
                & (((P.pcol("ss_list_price") >= P.plit(20.0 + 10 * i))
                    & (P.pcol("ss_list_price") <= P.plit(120.0 + 10 * i)))
                   | ((P.pcol("ss_coupon_amt") >= P.plit(5.0 * i))
                      & (P.pcol("ss_coupon_amt") <= P.plit(20.0 + 5.0 * i)))))
        agg = P.Aggregate(
            P.Filter(P.Scan("store_sales"), pred), keys=(),
            aggs=(
                P.AggSpec("ss_list_price", "mean", "avg_lp"),
                P.AggSpec("ss_list_price", "count", "cnt_lp"),
                P.AggSpec("ss_list_price", "nunique", "uniq_lp"),
            ),
        )
        branches.append(P.Project(agg, (
            ("band", P.plit(np.int32(i))),
            ("avg_lp", P.pcol("avg_lp")),
            ("cnt_lp", P.pcol("cnt_lp")),
            ("uniq_lp", P.pcol("uniq_lp")),
        )))
    return P.UnionAll(tuple(branches))


def q28(tables: Dict[str, Table]) -> Table:
    return _run(q28_plan(), tables, "q28")


def q15_plan(year: int = 2000, moy_lo: int = 1, moy_hi: int = 3,
             price: float = 120.0) -> P.Node:
    """TPC-DS q15 — the zip-band catalog star on the store channel:
    fact -> customer -> customer_address chain plus the date dim, kept
    rows are (zip band) OR (big ticket), revenue grouped by zip. The
    customer/address hops form a DEPENDENT join chain (the address key
    only exists after the customer join) — the enumeration's schema
    guard must keep that order while still reordering the independent
    date dim. SQL shape::

        SELECT ca_zip, sum(cs_sales_price)
        FROM catalog_sales, customer, customer_address, date_dim
        WHERE cs_bill_customer_sk = c_customer_sk
          AND c_current_addr_sk = ca_address_sk
          AND (substr(ca_zip,1,5) IN (...) OR cs_sales_price > 500)
          AND cs_sold_date_sk = d_date_sk AND d_qoy = :q AND d_year = :y
        GROUP BY ca_zip ORDER BY ca_zip
    """
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"),
                           (P.pcol("d_year") == P.plit(year))
                           & (P.pcol("d_moy") >= P.plit(moy_lo))
                           & (P.pcol("d_moy") <= P.plit(moy_hi))),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(x, P.Scan("customer"), on=(("ss_customer_sk", "c_customer_sk"),),
               bounded=True)
    x = P.Join(x, P.Scan("customer_address"),
               on=(("c_current_addr_sk", "ca_address_sk"),), bounded=True)
    zips = ((P.pcol("ca_zip5") < P.plit(40))
            | ((P.pcol("ca_zip5") >= P.plit(120)) & (P.pcol("ca_zip5") < P.plit(160)))
            | (P.pcol("ca_zip5") >= P.plit(260)))
    x = P.Filter(x, zips | (P.pcol("ss_sales_price") >= P.plit(price)))
    agg = P.Aggregate(x, keys=("ca_zip5",),
                      aggs=(P.AggSpec("ss_sales_price", "sum", "sum_sales"),))
    return P.Sort(agg, (("ca_zip5", True),))


def q15(tables: Dict[str, Table], year: int = 2000, moy_lo: int = 1,
        moy_hi: int = 3, price: float = 120.0) -> Table:
    return _run(q15_plan(year, moy_lo, moy_hi, price), tables, "q15")


def q8_plan(year: int = 2000, moy_lo: int = 10, moy_hi: int = 12,
            id_cut: int = 400) -> P.Node:
    """TPC-DS q8 — store revenue restricted to zip prefixes in the
    INTERSECT of a literal zip band and the zips of preferred
    customers; the set op lowers to a semi-join on deduped keys, and
    the store restriction is itself an EXISTS (semi-join) against that
    set. SQL shape::

        SELECT s_store_name, sum(ss_net_profit)
        FROM store_sales, date_dim, store,
          (SELECT zip FROM (zip_list INTERSECT
            SELECT ca_zip FROM customer_address, customer
            WHERE ca_address_sk = c_current_addr_sk
              AND c_preferred_cust_flag = 'Y' ...)) v
        WHERE ss_store_sk = s_store_sk AND d_qoy = ..
          AND substr(s_zip,1,2) = substr(v.zip,1,2)
        GROUP BY s_store_name

    ``c_customer_id < :cut`` stands in for the preferred flag, as
    dictionary codes do everywhere in this tier."""
    band = ((P.pcol("ca_zip5") < P.plit(30))
            | ((P.pcol("ca_zip5") >= P.plit(100)) & (P.pcol("ca_zip5") < P.plit(130)))
            | (P.pcol("ca_zip5") >= P.plit(270)))
    a1 = P.Project(P.Filter(P.Scan("customer_address"), band),
                   (("zip5", P.pcol("ca_zip5")),))
    pref = P.Join(P.Filter(P.Scan("customer"),
                           P.pcol("c_customer_id") < P.plit(id_cut)),
                  P.Scan("customer_address"),
                  on=(("c_current_addr_sk", "ca_address_sk"),), bounded=True)
    a2 = P.Project(pref, (("zip5", P.pcol("ca_zip5")),))
    zips = P.SetOp(a1, a2, "intersect")
    stores = P.Exists(P.Scan("store"), zips, on=(("s_zip5", "zip5"),))
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"),
                           (P.pcol("d_year") == P.plit(year))
                           & (P.pcol("d_moy") >= P.plit(moy_lo))
                           & (P.pcol("d_moy") <= P.plit(moy_hi))),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(x, stores, on=(("ss_store_sk", "s_store_sk"),), bounded=True)
    agg = P.Aggregate(x, keys=("ss_store_sk",),
                      aggs=(P.AggSpec("ss_ext_sales_price", "sum", "net"),))
    return P.Sort(agg, (("ss_store_sk", True),))


def q8(tables: Dict[str, Table], year: int = 2000, moy_lo: int = 10,
       moy_hi: int = 12, id_cut: int = 400) -> Table:
    return _run(q8_plan(year, moy_lo, moy_hi, id_cut), tables, "q8")


def q34_plan(year: int = 2000, moy_lo: int = 4, moy_hi: int = 6,
             buys=(0, 3), lo: int = 1, hi: int = 3) -> P.Node:
    """TPC-DS q34 — q73's wider HAVING band: per-(ticket, customer)
    item counts in a count band, demographic filter includes the
    vehicle lane, join-back to customer. SQL shape::

        SELECT c_customer_id, cnt FROM (
          SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
          FROM store_sales, date_dim, household_demographics
          WHERE ss_sold_date_sk = d_date_sk AND ss_hdemo_sk = hd_demo_sk
            AND d_year = :y AND d_moy BETWEEN :l AND :h
            AND hd_buy_potential IN (:b1, :b2) AND hd_vehicle_count > 0
          GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
        WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN :lo AND :hi
        ORDER BY cnt DESC, c_customer_id
    """
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"),
                           (P.pcol("d_year") == P.plit(year))
                           & (P.pcol("d_moy") >= P.plit(moy_lo))
                           & (P.pcol("d_moy") <= P.plit(moy_hi))),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(
        x,
        P.Filter(P.Scan("household_demographics"),
                 ((P.pcol("hd_buy_potential") == P.plit(buys[0]))
                  | (P.pcol("hd_buy_potential") == P.plit(buys[1])))
                 & (P.pcol("hd_vehicle_count") > P.plit(0))),
        on=(("ss_hdemo_sk", "hd_demo_sk"),), bounded=True,
    )
    agg = P.Aggregate(x, keys=("ss_ticket_number", "ss_customer_sk"),
                      aggs=(P.AggSpec(None, "count_all", "cnt"),))
    hv = P.Having(agg, (P.pcol("cnt") >= P.plit(lo)) & (P.pcol("cnt") <= P.plit(hi)))
    j = P.Join(hv, P.Scan("customer"), on=(("ss_customer_sk", "c_customer_sk"),))
    proj = P.Project(j, (("c_customer_id", P.pcol("c_customer_id")),
                         ("cnt", P.pcol("cnt"))))
    return P.Sort(proj, (("cnt", False), ("c_customer_id", True)))


def q34(tables: Dict[str, Table], year: int = 2000, moy_lo: int = 4,
        moy_hi: int = 6, buys=(0, 3), lo: int = 1, hi: int = 3) -> Table:
    return _run(q34_plan(year, moy_lo, moy_hi, buys, lo, hi), tables, "q34")


def q39_plan(cov: float = 0.55) -> P.Node:
    """TPC-DS q39 — the native stdev/mean shape: per-(store, month)
    quantity mean and sample standard deviation, kept where the
    coefficient of variation clears a bar (HAVING over agg outputs).
    SQL shape::

        SELECT w_warehouse_sk, d_moy, avg(inv_quantity_on_hand) mean,
               stddev_samp(inv_quantity_on_hand) stdev
        FROM inventory, date_dim, warehouse WHERE ...
        GROUP BY w_warehouse_sk, d_moy
        HAVING stdev / mean > 1.0

    store_sales/store stand in for inventory/warehouse (same relational
    shape; the var/std aggregates are the part under test)."""
    x = P.Join(P.Scan("store_sales"), P.Scan("date_dim"),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    agg = P.Aggregate(
        x, keys=("ss_store_sk", "d_moy"),
        aggs=(
            P.AggSpec("ss_quantity", "mean", "mean_q"),
            P.AggSpec("ss_quantity", "std", "std_q"),
        ),
    )
    hv = P.Having(agg, P.pcol("std_q") > P.pcol("mean_q") * P.plit(cov))
    return P.Sort(hv, (("ss_store_sk", True), ("d_moy", True)))


def q39(tables: Dict[str, Table], cov: float = 0.55) -> Table:
    return _run(q39_plan(cov), tables, "q39")


def q30_plan(year: int = 1999, factor: float = 1.2) -> P.Node:
    """TPC-DS q30 — the STATE-level decorrelation (q1's shape one
    grouping level up): per-(customer, state) return totals vs the
    per-state average * 1.2. SQL shape::

        WITH customer_total_return AS (
          SELECT wr_returning_customer_sk ctr_customer_sk, ca_state,
                 sum(wr_return_amt) ctr_total_return
          FROM web_returns, date_dim, customer_address WHERE d_year = :y ...
          GROUP BY wr_returning_customer_sk, ca_state)
        SELECT c_customer_id, ... FROM customer_total_return ctr1, customer
        WHERE ctr1.ctr_total_return >
              (SELECT avg(ctr_total_return) * 1.2 FROM customer_total_return
               ctr2 WHERE ctr1.ca_state = ctr2.ca_state)
          AND ctr1.ctr_customer_sk = c_customer_sk
        ORDER BY c_customer_id LIMIT 100

    store_returns/store(s_state) stand in for web_returns/
    customer_address(ca_state)."""
    ctr = P.Aggregate(
        P.Join(
            P.Join(
                P.Scan("store_returns"),
                P.Filter(P.Scan("date_dim"), P.pcol("d_year") == P.plit(year)),
                on=(("sr_returned_date_sk", "d_date_sk"),), bounded=True,
            ),
            P.Scan("store"),
            on=(("sr_store_sk", "s_store_sk"),), bounded=True,
        ),
        keys=("sr_customer_sk", "s_state"),
        aggs=(P.AggSpec("sr_return_amt", "sum", "ctr_total_return"),),
    )
    x = P.CorrelatedAggFilter(
        ctr, ctr, on=("s_state", "s_state"),
        agg=P.AggSpec("ctr_total_return", "mean", "ctr_avg"),
        predicate=P.pcol("ctr_total_return") > P.pcol("ctr_avg") * P.plit(factor),
    )
    x = P.Join(x, P.Scan("customer"), on=(("sr_customer_sk", "c_customer_sk"),))
    x = P.Project(x, (("c_customer_id", P.pcol("c_customer_id")),
                      ("ctr_total_return", P.pcol("ctr_total_return"))))
    # a customer can clear the bar in several states — the total is a
    # deterministic tie-break for those duplicate ids
    return P.Limit(P.Sort(x, (("c_customer_id", True),
                              ("ctr_total_return", True))), 100)


def q30(tables: Dict[str, Table], year: int = 1999, factor: float = 1.2) -> Table:
    return _run(q30_plan(year, factor), tables, "q30")


def q32_plan(category: int = 4, lo: int = 300, hi: int = 390,
             factor: float = 1.3) -> P.Node:
    """TPC-DS q32 — q92's catalog-channel twin (excess discount): sum
    of discounts exceeding 1.3x the per-item average inside a date
    window; the date-filtered fact is ONE shared node on both sides of
    the correlation. SQL shape::

        SELECT sum(cs_ext_discount_amt)
        FROM catalog_sales, item, date_dim
        WHERE i_manufact_id = :m AND i_item_sk = cs_item_sk
          AND d_date_sk = cs_sold_date_sk AND d_date BETWEEN :lo AND :hi
          AND cs_ext_discount_amt >
              (SELECT 1.3 * avg(cs_ext_discount_amt) FROM catalog_sales,
               date_dim WHERE cs_item_sk = i_item_sk AND ...)

    cs_coupon_amt stands in for the discount lane; the category id
    stands in for the manufacturer filter."""
    dated = P.Join(
        P.Scan("catalog_sales"),
        P.Filter(P.Scan("date_dim"),
                 (P.pcol("d_date_sk") >= P.plit(lo))
                 & (P.pcol("d_date_sk") <= P.plit(hi))),
        on=(("cs_sold_date_sk", "d_date_sk"),), bounded=True,
    )
    main = P.Join(
        dated,
        P.Filter(P.Scan("item"), P.pcol("i_category_id") == P.plit(category)),
        on=(("cs_item_sk", "i_item_sk"),), bounded=True,
    )
    x = P.CorrelatedAggFilter(
        main, dated, on=("cs_item_sk", "cs_item_sk"),
        agg=P.AggSpec("cs_coupon_amt", "mean", "avg_disc"),
        predicate=P.pcol("cs_coupon_amt") > P.plit(factor) * P.pcol("avg_disc"),
    )
    return P.Aggregate(x, keys=(),
                       aggs=(P.AggSpec("cs_coupon_amt", "sum", "excess"),))


def q32(tables: Dict[str, Table], category: int = 4, lo: int = 300,
        hi: int = 390, factor: float = 1.3) -> Table:
    return _run(q32_plan(category, lo, hi, factor), tables, "q32")


def _any_channel_active(year: int, moy_lo: int, moy_hi: int) -> P.Node:
    """Customer sks with web OR catalog activity in the window — the
    OR of two EXISTS is one EXISTS over the UNION ALL of the
    subqueries, which is how the q10/q35 family lowers."""
    dates = P.Filter(P.Scan("date_dim"),
                     (P.pcol("d_year") == P.plit(year))
                     & (P.pcol("d_moy") >= P.plit(moy_lo))
                     & (P.pcol("d_moy") <= P.plit(moy_hi)))
    web = P.Join(P.Scan("web_sales"), dates,
                 on=(("ws_sold_date_sk", "d_date_sk"),), bounded=True)
    cat = P.Join(P.Scan("catalog_sales"), dates,
                 on=(("cs_sold_date_sk", "d_date_sk"),), bounded=True)
    return P.UnionAll((
        P.Project(web, (("any_customer_sk", P.pcol("ws_bill_customer_sk")),)),
        P.Project(cat, (("any_customer_sk", P.pcol("cs_ship_customer_sk")),)),
    ))


def q10_plan(states=(1, 4, 7), year: int = 1999, moy_lo: int = 1,
             moy_hi: int = 4) -> P.Node:
    """TPC-DS q10 — demographic counts of in-county customers with
    store activity AND (web OR catalog) activity in the window: the OR
    of EXISTS lowers as one EXISTS over a UNION ALL, then both EXISTS
    become semi-joins. SQL shape::

        SELECT cd_gender, cd_marital_status, cd_education_status, count(*)
        FROM customer c, customer_address ca, customer_demographics
        WHERE c_current_addr_sk = ca_address_sk AND ca_county IN (...)
          AND cd_demo_sk = c_current_cdemo_sk
          AND EXISTS (SELECT * FROM store_sales, date_dim WHERE ...)
          AND (EXISTS (SELECT * FROM web_sales, date_dim WHERE ...)
               OR EXISTS (SELECT * FROM catalog_sales, date_dim WHERE ...))
        GROUP BY ... ORDER BY ...
    """
    in_states = None
    for s in states:
        e = P.pcol("ca_state") == P.plit(s)
        in_states = e if in_states is None else (in_states | e)
    dates = P.Filter(P.Scan("date_dim"),
                     (P.pcol("d_year") == P.plit(year))
                     & (P.pcol("d_moy") >= P.plit(moy_lo))
                     & (P.pcol("d_moy") <= P.plit(moy_hi)))
    x = P.Join(P.Scan("customer"),
               P.Filter(P.Scan("customer_address"), in_states),
               on=(("c_current_addr_sk", "ca_address_sk"),), bounded=True)
    x = P.Join(x, P.Scan("customer_demographics"),
               on=(("c_current_cdemo_sk", "cd_demo_sk"),), bounded=True)
    x = P.Exists(x, P.Join(P.Scan("store_sales"), dates,
                           on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True),
                 on=(("c_customer_sk", "ss_customer_sk"),))
    x = P.Exists(x, _any_channel_active(year, moy_lo, moy_hi),
                 on=(("c_customer_sk", "any_customer_sk"),))
    agg = P.Aggregate(
        x, keys=("cd_gender", "cd_marital_status", "cd_education_status"),
        aggs=(P.AggSpec(None, "count_all", "cnt"),),
    )
    return P.Sort(agg, (("cd_gender", True), ("cd_marital_status", True),
                        ("cd_education_status", True)))


def q10(tables: Dict[str, Table], states=(1, 4, 7), year: int = 1999,
        moy_lo: int = 1, moy_hi: int = 4) -> Table:
    return _run(q10_plan(states, year, moy_lo, moy_hi), tables, "q10")


def q35_plan(year: int = 1999, moy_lo: int = 1, moy_hi: int = 6) -> P.Node:
    """TPC-DS q35 — q10's reporting sibling: state-level demographic
    stats (count plus max/sum/avg of the dependent count) over the same
    EXISTS-store AND (EXISTS-web OR EXISTS-catalog) population. SQL
    shape::

        SELECT ca_state, cd_gender, cd_marital_status, count(*),
               max(cd_dep_count), sum(cd_dep_count), avg(cd_dep_count)
        FROM customer c, customer_address ca, customer_demographics
        WHERE c_current_addr_sk = ca_address_sk
          AND cd_demo_sk = c_current_cdemo_sk
          AND EXISTS (...store...) AND (EXISTS (...web...) OR EXISTS (...catalog...))
        GROUP BY ca_state, cd_gender, cd_marital_status ORDER BY ...
    """
    dates = P.Filter(P.Scan("date_dim"),
                     (P.pcol("d_year") == P.plit(year))
                     & (P.pcol("d_moy") >= P.plit(moy_lo))
                     & (P.pcol("d_moy") <= P.plit(moy_hi)))
    x = P.Join(P.Scan("customer"), P.Scan("customer_address"),
               on=(("c_current_addr_sk", "ca_address_sk"),), bounded=True)
    x = P.Join(x, P.Scan("customer_demographics"),
               on=(("c_current_cdemo_sk", "cd_demo_sk"),), bounded=True)
    x = P.Exists(x, P.Join(P.Scan("store_sales"), dates,
                           on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True),
                 on=(("c_customer_sk", "ss_customer_sk"),))
    x = P.Exists(x, _any_channel_active(year, moy_lo, moy_hi),
                 on=(("c_customer_sk", "any_customer_sk"),))
    agg = P.Aggregate(
        x, keys=("ca_state", "cd_gender", "cd_marital_status"),
        aggs=(
            P.AggSpec(None, "count_all", "cnt"),
            P.AggSpec("cd_dep_count", "max", "max_dep"),
            P.AggSpec("cd_dep_count", "sum", "sum_dep"),
            P.AggSpec("cd_dep_count", "mean", "avg_dep"),
        ),
    )
    return P.Sort(agg, (("ca_state", True), ("cd_gender", True),
                        ("cd_marital_status", True)))


def q35(tables: Dict[str, Table], year: int = 1999, moy_lo: int = 1,
        moy_hi: int = 6) -> Table:
    return _run(q35_plan(year, moy_lo, moy_hi), tables, "q35")


# ---------------------------------------------------------------------------
# hand-built greens re-expressed as plans (bit-identity contract)
# ---------------------------------------------------------------------------


def q3_plan(manufact_id: int = 128, month: int = 11) -> P.Node:
    """``models/tpcds.py::q3`` as IR: same dense bounded-domain star
    joins, same group keys, same ORDER BY — the compiled plan's output
    must be BIT-identical to the hand-fused original."""
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"), P.pcol("d_moy") == P.plit(month)),
               on=(("ss_sold_date_sk", "d_date_sk"),), bounded=True)
    x = P.Join(x, P.Filter(P.Scan("item"),
                           P.pcol("i_manufact_id") == P.plit(manufact_id)),
               on=(("ss_item_sk", "i_item_sk"),), bounded=True)
    agg = P.Aggregate(
        x, keys=("d_year", "i_brand_id"),
        aggs=(P.AggSpec("ss_ext_sales_price", "sum", "ss_ext_sales_price_sum"),),
    )
    return P.Sort(agg, (("d_year", True), ("ss_ext_sales_price_sum", False),
                        ("i_brand_id", True)))


def q55_plan(manager_id: int = 28, month: int = 11, year: int = 1999) -> P.Node:
    """``models/tpcds.py::q55`` as IR: the sort-merge star (no bounded
    hint, matching the hand pipeline's num_keys=None lowering)."""
    x = P.Scan("store_sales")
    x = P.Join(x, P.Filter(P.Scan("date_dim"),
                           (P.pcol("d_moy") == P.plit(month))
                           & (P.pcol("d_year") == P.plit(year))),
               on=(("ss_sold_date_sk", "d_date_sk"),))
    x = P.Join(x, P.Filter(P.Scan("item"),
                           P.pcol("i_manager_id") == P.plit(manager_id)),
               on=(("ss_item_sk", "i_item_sk"),))
    agg = P.Aggregate(x, keys=("i_brand_id",),
                      aggs=(P.AggSpec("ss_ext_sales_price", "sum", "ext_price"),))
    return P.Sort(agg, (("ext_price", False), ("i_brand_id", True)))


# ---------------------------------------------------------------------------
# registry (tests, ledger, and the premerge compiler tier iterate this)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlanQueryDef:
    name: str
    gen: Callable[[int, int], Dict[str, Table]]
    plan: Callable[[], "P.Node"]
    run: Callable[[Dict[str, Table]], Table]
    rows: int  # default oracle scale


PLAN_QUERIES: Dict[str, PlanQueryDef] = {
    d.name: d
    for d in (
        PlanQueryDef("q1", lambda n, s=21: gen_store_returns(n, seed=s),
                     q1_plan, q1, 8000),
        PlanQueryDef("q8", lambda n, s=42: gen_store_wide(n, seed=s),
                     q8_plan, q8, 10000),
        PlanQueryDef("q9", lambda n, s=42: gen_store_wide(n, seed=s),
                     q9_plan, q9, 10000),
        PlanQueryDef("q10", lambda n, s=29: gen_channels(n, seed=s),
                     q10_plan, q10, 6000),
        PlanQueryDef("q13", lambda n, s=42: gen_store_wide(n, seed=s),
                     q13_plan, q13, 10000),
        PlanQueryDef("q15", lambda n, s=42: gen_store_wide(n, seed=s),
                     q15_plan, q15, 10000),
        PlanQueryDef("q20", lambda n, s=23: gen_catalog(n, seed=s),
                     q20_plan, q20, 10000),
        PlanQueryDef("q26", lambda n, s=23: gen_catalog(n, seed=s),
                     q26_plan, q26, 10000),
        PlanQueryDef("q27", lambda n, s=42: gen_store_wide(n, seed=s),
                     q27_plan, q27, 10000),
        PlanQueryDef("q28", lambda n, s=42: gen_store_wide(n, seed=s),
                     q28_plan, q28, 10000),
        PlanQueryDef("q30", lambda n, s=21: gen_store_returns(n, seed=s),
                     q30_plan, q30, 8000),
        PlanQueryDef("q32", lambda n, s=23: gen_catalog(n, seed=s),
                     q32_plan, q32, 10000),
        PlanQueryDef("q34", lambda n, s=42: gen_store_wide(n, seed=s),
                     q34_plan, q34, 10000),
        PlanQueryDef("q35", lambda n, s=29: gen_channels(n, seed=s),
                     q35_plan, q35, 6000),
        PlanQueryDef("q38", lambda n, s=29: gen_channels(n, seed=s),
                     q38_plan, q38, 6000),
        PlanQueryDef("q39", lambda n, s=42: gen_store_wide(n, seed=s),
                     q39_plan, q39, 10000),
        PlanQueryDef("q43", lambda n, s=42: gen_store_wide(n, seed=s),
                     q43_plan, q43, 10000),
        PlanQueryDef("q48", lambda n, s=42: gen_store_wide(n, seed=s),
                     q48_plan, q48, 10000),
        PlanQueryDef("q65", lambda n, s=42: gen_store_wide(n, seed=s),
                     q65_plan, q65, 10000),
        PlanQueryDef("q69", lambda n, s=29: gen_channels(n, seed=s),
                     q69_plan, q69, 6000),
        PlanQueryDef("q73", lambda n, s=42: gen_store_wide(n, seed=s),
                     q73_plan, q73, 10000),
        PlanQueryDef("q87", lambda n, s=29: gen_channels(n, seed=s),
                     q87_plan, q87, 6000),
        PlanQueryDef("q88", lambda n, s=42: gen_store_wide(n, seed=s),
                     q88_plan, q88, 10000),
        PlanQueryDef("q92", lambda n, s=7: gen_web(n, seed=s),
                     q92_plan, q92, 8000),
        PlanQueryDef("q96", lambda n, s=42: gen_store_wide(n, seed=s),
                     q96_plan, q96, 10000),
    )
}

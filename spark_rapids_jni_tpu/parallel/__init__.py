"""Parallel tier: device mesh, executor binding, ICI exchange.

The reference delegates all distribution upward to Spark (SURVEY §2.9):
its only multi-device machinery is per-call ``auto_set_device`` and
per-thread CUDA streams, with the UCX shuffle living in the plugin.
Here the exchange is first-class and TPU-native: ``jax.sharding.Mesh``
over ICI (with a DCN outer axis for multi-pod), ``shard_map`` +
``lax.all_to_all`` for the repartition collective, and static-shape
bucket framing so the whole shuffle compiles into one XLA program.
"""

from . import (  # noqa: F401
    device,
    distributed,
    join_distributed,
    mesh,
    shuffle,
    sort_distributed,
    table_ops,
)

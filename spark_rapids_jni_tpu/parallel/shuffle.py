"""Shuffle: hash partition + the ICI all-to-all exchange.

Replaces the UCX/NVLink RapidsShuffleManager path (SURVEY §2.9, §5
"distributed communication backend"): executor-partitioned row batches
are repartitioned with ONE ``lax.all_to_all`` over the mesh's data axis
inside ``shard_map`` — on-pod exchanges ride ICI; put a ``dcn`` outer
axis on the mesh and XLA layers the collective across pods.

Static-shape framing (XLA compiles one program, no data-dependent
shapes): each shard scatters its rows into a [P, capacity] bucket
matrix + occupancy mask, all_to_all swaps bucket axes, receivers get
[P, capacity] from every peer. ``capacity`` bounds rows any shard may
send to one destination; overflow RAISES RetryableError by default
(no silent-drop path — VERDICT r3 item 8), with ``on_overflow="flag"``
as the opt-in contract for capacity-managing callers that recompute
and retry, and ``on_overflow="retry"`` as the self-healing contract:
the exchange doubles capacity (geometric, bounded) and re-executes
in-op (utils/retry.py orchestrator counters record each escalation).
Compaction back to dense rows happens host-side or in the consuming
kernel via the mask.

Observability (utils/metrics.py, SRJT_METRICS_ENABLED=1): every
exchange execution records its WIRE footprint — the capacity-padded
[n_parts, capacity] bucket bytes the collective actually moves, per
attempt, not the dense row payload — into
``shuffle.bytes_exchanged``; a completed exchange adds a wall-clock
histogram entry (``shuffle.exchange_us``) and an event-log line, and
each capacity escalation bumps ``shuffle.capacity_retries`` and logs
the old->new capacity — the Thallus-style transport-layer
instrumentation the VERDICT scan->agg GB/s artifacts read.

Integrity (ISSUE 5, utils/integrity.py): with checks armed (the
default) every completed exchange verifies an order-independent
payload checksum — the wraparound-u64 sum of every lane's bit pattern,
invariant under the row permutation the collective performs — plus the
occupied-slot count against the rows sent. A mismatch raises retryable
``DataCorruption`` (op_boundary's armed retry re-executes the
exchange), counted under ``sidecar.integrity.crc_mismatch`` — the
Thallus posture: transport corruption must be an error, never rows.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..columnar import Table
from ..columnar.dtype import TypeId
from ..ops.hashing import hash_partition_map
from ..ops.copying import gather
from ..utils.dispatch import op_boundary
from ._smcache import cached_sm, shard_map

__all__ = ["hash_partition", "all_to_all_exchange", "exchange_by_key"]


@op_boundary("hash_partition")
def hash_partition(table: Table, num_partitions: int, key_cols: Sequence[str]) -> Tuple[Table, List[int]]:
    """Single-device cudf-style hash_partition: rows reordered so each
    partition is contiguous; returns (table, partition start offsets)."""
    pmap = hash_partition_map([table.column(c) for c in key_cols], num_partitions)
    order = jnp.argsort(pmap, stable=True).astype(jnp.int32)
    out = gather(table, order)
    counts = np.bincount(np.asarray(pmap), minlength=num_partitions)
    offsets = np.concatenate([[0], np.cumsum(counts)])[:-1].tolist()
    return out, offsets


def _exchange_checksum(arrays) -> int:
    """Order-independent payload checksum for the all-to-all (ISSUE 5,
    utils/integrity.py): the exchange PERMUTES rows across shards, so a
    positional CRC cannot survive it — the invariant is the byte
    MULTISET, summarized as the wraparound-u64 sum of every lane's bit
    pattern. Unoccupied bucket slots are zero-initialized and add
    nothing, so the sum over the capacity-padded receive buffers equals
    the sum over the dense send payload exactly when every row landed
    intact. Computed on device (one reduction per array), no host copy."""
    from jax import lax as _lax

    total = 0
    for a in arrays:
        if a.dtype == jnp.bool_:
            v = a.astype(jnp.uint8)
        else:
            v = _lax.bitcast_convert_type(
                a, jnp.dtype(f"uint{a.dtype.itemsize * 8}")
            )
        total = (total + int(jnp.sum(v.astype(jnp.uint64)))) & 0xFFFFFFFFFFFFFFFF
    return total


def _bucketize(vals: jnp.ndarray, dest: jnp.ndarray, n_parts: int, capacity: int):
    """Per-shard scatter of [n] rows into [P, capacity] buckets.

    Returns (buckets, mask, overflow). Rows beyond capacity for their
    destination are dropped and flagged.
    """
    n = dest.shape[0]
    order = jnp.argsort(dest)  # group rows by destination
    d_sorted = dest[order]
    # position within destination bucket: index along the sorted run
    idx = jnp.arange(n, dtype=jnp.int32)
    run_start = jnp.searchsorted(d_sorted, jnp.arange(n_parts, dtype=jnp.int32), side="left")
    slot = idx - run_start[d_sorted]
    overflow = jnp.any(slot >= capacity)
    keep = slot < capacity
    # overflowing rows scatter out of range and are dropped (mode="drop"),
    # never aliasing the legitimate occupant of the last slot
    flat = jnp.where(keep, d_sorted.astype(jnp.int32) * capacity + slot, n_parts * capacity)

    shape = (n_parts * capacity,) + vals.shape[1:]
    buckets = jnp.zeros(shape, vals.dtype)
    buckets = buckets.at[flat].set(vals[order], mode="drop")
    mask = jnp.zeros((n_parts * capacity,), bool).at[flat].set(True, mode="drop")
    return (
        buckets.reshape((n_parts, capacity) + vals.shape[1:]),
        mask.reshape(n_parts, capacity),
        overflow,
    )


def _exchange_once(arrays, dest, mesh: Mesh, axis: str, capacity: int, n_parts: int):
    """One all-to-all execution at a fixed capacity."""

    def body(dest_local, *arrs):
        outs = []
        ovf = jnp.zeros((), bool)
        mask = None
        for a in arrs:
            b, m, o = _bucketize(a, dest_local, n_parts, capacity)
            # all_to_all: split axis 0 (destinations), concat received
            r = lax.all_to_all(b, axis, split_axis=0, concat_axis=0, tiled=True)
            outs.append(r)
            ovf = ovf | o
            mask = m
        rm = lax.all_to_all(mask, axis, split_axis=0, concat_axis=0, tiled=True)
        return tuple(outs) + (rm, ovf[None])

    spec = P(axis)
    in_specs = (spec,) + tuple(spec for _ in arrays)
    out_specs = tuple(spec for _ in arrays) + (spec, spec)
    f = cached_sm(
        ("a2a_exchange", mesh, axis, int(capacity), len(arrays),
         tuple(str(a.dtype) for a in arrays)),
        lambda: jax.jit(shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs)),
    )
    *received, recv_mask, overflow = f(dest, *arrays)
    return received, recv_mask, overflow


@op_boundary("all_to_all_exchange")
def all_to_all_exchange(
    arrays: Sequence[jnp.ndarray],
    dest: jnp.ndarray,
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    on_overflow: str = "raise",
):
    """Exchange row-sharded arrays so row i lands on shard dest[i].

    arrays: row-sharded along `axis` ([N_global, ...] each); dest:
    [N_global] int32 in [0, mesh axis size). Returns (received_arrays,
    recv_mask, overflow): received arrays are [P * capacity * ...] per
    shard, i.e. globally [N_shards, P, capacity, ...] flattened on the
    leading axis, with recv_mask marking occupied slots.

    Overflow semantics (VERDICT r3 item 8): a caller-supplied capacity
    that a skewed destination exceeds can NOT silently hand back
    truncated data. ``on_overflow="raise"`` (default) raises
    ``RetryableError`` — the Spark task-retry class; capacity-managing
    callers (the Table tier recomputes and retries) opt into the
    flag-only contract with ``on_overflow="flag"``; and
    ``on_overflow="retry"`` closes the loop IN-OP: the exchange doubles
    the capacity (geometric, bounded by the per-shard ceiling that
    cannot overflow) and re-executes until every row lands — the UCX
    shuffle transient-failure posture, wired through the retry
    orchestrator's counters (utils/retry.py). The defaulted capacity
    (= rows per shard) cannot overflow.
    """
    if on_overflow not in ("raise", "flag", "retry"):
        raise ValueError(
            f"on_overflow must be 'raise', 'flag', or 'retry', got {on_overflow!r}"
        )
    if capacity is not None and capacity < 1:
        # capacity=0 would make the geometric escalation a fixed point
        # (2*0 == 0): the retry loop must always be able to grow
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    n_parts = mesh.shape[axis]
    n_global = dest.shape[0]
    per_shard = n_global // n_parts
    if capacity is None:
        capacity = per_shard  # safe: one shard can absorb everything

    from .. import memgov
    from ..utils import integrity, metrics

    armed = metrics.is_enabled()
    governed = on_overflow == "retry" and memgov.is_enabled()
    # integrity (ISSUE 5): checksum the payload entering the collective
    # so a corrupted/dropped row surfaces as retryable DataCorruption
    # (op_boundary's armed retry re-executes), never as wrong rows
    checked = integrity.is_enabled()
    sent_sum = _exchange_checksum(arrays) if checked else None
    # per-GLOBAL-ROW wire cost: the collective moves capacity-padded
    # [n_parts, capacity] buckets per shard per array (NOT the dense
    # row payload) plus the 1-byte/slot occupancy mask — the padded
    # footprint is what a GB/s artifact must divide by, and it changes
    # each time the escalation loop doubles capacity. ONE cost model:
    # the metrics wire accounting and the governor's escalation
    # estimate read the same number
    row_bytes = (
        sum(int(a.nbytes) // max(a.shape[0], 1) for a in arrays) + 1
        if armed or governed else 0
    )
    t0 = time.perf_counter() if armed else 0.0
    wire_bytes = 0
    while True:
        received, recv_mask, overflow = _exchange_once(
            arrays, dest, mesh, axis, int(capacity), n_parts
        )
        if armed:
            # bytes THIS execution put on the wire (failed-overflow
            # attempts moved their buckets too, so accumulate per try)
            attempt_bytes = n_parts * n_parts * int(capacity) * row_bytes
            wire_bytes += attempt_bytes
            metrics.counter("shuffle.bytes_exchanged").inc(attempt_bytes)
        overflowed = bool(np.asarray(overflow).any())
        if not overflowed or on_overflow == "flag":
            if checked and not overflowed:
                # verify only complete exchanges: a flagged overflow
                # legitimately dropped rows, which is the CALLER's
                # recompute contract, not corruption
                from ..utils import metrics as _m

                _m.registry().counter("sidecar.integrity.exchanges_checked").inc()
                recv_sum = _exchange_checksum(received)
                recv_rows = int(jnp.sum(recv_mask.astype(jnp.uint64)))
                if recv_sum != sent_sum or recv_rows != int(n_global):
                    raise integrity.raise_corruption(
                        "shuffle.exchange",
                        f"sent 0x{sent_sum:016x}/{int(n_global)} rows != "
                        f"recv 0x{recv_sum:016x}/{recv_rows} rows",
                    )
            if armed:
                elapsed = time.perf_counter() - t0
                metrics.counter("shuffle.exchanges").inc()
                metrics.histogram("shuffle.exchange_us").record(elapsed * 1e6)
                metrics.event(
                    "shuffle.exchange", axis=axis, n_parts=n_parts,
                    capacity=int(capacity), wire_bytes=wire_bytes,
                    wall_us=round(elapsed * 1e6, 1),
                    overflow=overflowed,
                )
            return received, recv_mask, overflow
        if on_overflow == "retry" and capacity < per_shard:
            # the capacity re-try loop consults the deadline/cancel
            # token BETWEEN attempts (utils/deadline.py): an escalated
            # re-execution never starts once the query budget is gone
            from ..utils import deadline as deadline_mod

            deadline_mod.check("all_to_all_exchange.capacity_retry")
            # geometric escalation: at most ceil(log2(per_shard/cap0))
            # re-executions before the cannot-overflow ceiling
            new_capacity = min(2 * int(capacity), per_shard)
            # memory governor (memgov/, ISSUE 4): the doubled bucket
            # matrices are a footprint the op's original admission never
            # covered — route the escalated estimate through the
            # controller (which GROWS the held admission on success) so
            # a doubling that cannot fit spills cold catalog buffers or
            # raises the retryable MemoryBudgetExceeded (the split
            # path), never an XLA OOM
            if governed:
                from ..utils.memory import exchange_bytes_estimate

                memgov.ensure_fits(
                    exchange_bytes_estimate(
                        row_bytes, n_parts, int(new_capacity)
                    ),
                    "all_to_all_exchange.capacity_retry",
                )
            metrics.event(
                "shuffle.capacity_escalation", axis=axis,
                capacity=int(capacity), new_capacity=int(new_capacity),
            )
            capacity = new_capacity
            from ..utils import retry as retry_mod

            retry_mod.record_capacity_retry()
            continue
        from ..utils.errors import RetryableError

        raise RetryableError(
            f"all_to_all_exchange: a destination shard received more than "
            f"capacity={capacity} rows; retry with a larger capacity "
            f"(rows would otherwise be dropped)"
        )


@op_boundary("exchange_by_key")
def exchange_by_key(
    table: Table,
    key_cols: Sequence[str],
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    on_overflow: str = "raise",
):
    """Hash-repartition a row-sharded fixed-width Table over the mesh.

    Returns (pairs_by_column, recv_mask, overflow) where each pair is
    (data, validity-or-None) — null masks travel with their column so
    null rows stay null on the receiving shard. Rows of one key all land
    on the same shard (hash pmod, ops/hashing parity with the
    single-device partitioner).

    ``on_overflow="retry"`` makes a capacity overflow self-healing: the
    exchange doubles ``capacity`` (geometric, bounded by the per-shard
    ceiling) and re-executes the all-to-all instead of raising — the
    shuffle-side half of the retry orchestrator (utils/retry.py).
    """
    if on_overflow not in ("raise", "flag", "retry"):
        raise ValueError(
            f"on_overflow must be 'raise', 'flag', or 'retry', got {on_overflow!r}"
        )
    for c in table.columns:
        if c.dtype.id in (TypeId.STRING, TypeId.LIST):
            raise ValueError(
                "exchange_by_key moves fixed-width payloads; use "
                "parallel.table_ops.exchange_table, which dictionary-encodes "
                "string columns automatically"
            )
    dest = hash_partition_map([table.column(c) for c in key_cols], mesh.shape[axis])
    arrays: List[jnp.ndarray] = []
    has_validity: List[bool] = []
    for c in table.columns:
        arrays.append(c.data)
        has_validity.append(c.validity is not None)
        if c.validity is not None:
            arrays.append(c.validity)
    received, recv_mask, overflow = all_to_all_exchange(
        arrays, dest.astype(jnp.int32), mesh, axis, capacity, on_overflow=on_overflow
    )
    pairs = []
    it = iter(received)
    for nullable in has_validity:
        data = next(it)
        pairs.append((data, next(it) if nullable else None))
    return pairs, recv_mask, overflow

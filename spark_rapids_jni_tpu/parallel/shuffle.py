"""Shuffle: hash partition + the ICI all-to-all exchange.

Replaces the UCX/NVLink RapidsShuffleManager path (SURVEY §2.9, §5
"distributed communication backend"): executor-partitioned row batches
are repartitioned with ONE ``lax.all_to_all`` over the mesh's data axis
inside ``shard_map`` — on-pod exchanges ride ICI; put a ``dcn`` outer
axis on the mesh and XLA layers the collective across pods.

Static-shape framing (XLA compiles one program, no data-dependent
shapes): each shard scatters its rows into a [P, capacity] bucket
matrix + occupancy mask, all_to_all swaps bucket axes, receivers get
[P, capacity] from every peer. ``capacity`` bounds rows any shard may
send to one destination; overflow RAISES RetryableError by default
(no silent-drop path — VERDICT r3 item 8), with ``on_overflow="flag"``
as the opt-in contract for capacity-managing callers that recompute
and retry, and ``on_overflow="retry"`` as the self-healing contract:
the exchange doubles capacity (geometric, bounded) and re-executes
in-op (utils/retry.py orchestrator counters record each escalation).
Compaction back to dense rows happens host-side or in the consuming
kernel via the mask.

Observability (utils/metrics.py, SRJT_METRICS_ENABLED=1): every
exchange execution records its WIRE footprint — the capacity-padded
[n_parts, capacity] bucket bytes the collective actually moves, per
attempt, not the dense row payload — into
``shuffle.bytes_exchanged``; a completed exchange adds a wall-clock
histogram entry (``shuffle.exchange_us``) and an event-log line, and
each capacity escalation bumps ``shuffle.capacity_retries`` and logs
the old->new capacity — the Thallus-style transport-layer
instrumentation the VERDICT scan->agg GB/s artifacts read.

Integrity (ISSUE 5, utils/integrity.py): with checks armed (the
default) every completed exchange verifies an order-independent
payload checksum — the wraparound-u64 sum of every lane's bit pattern,
invariant under the row permutation the collective performs — plus the
occupied-slot count against the rows sent. A mismatch raises retryable
``DataCorruption`` (op_boundary's armed retry re-executes the
exchange), counted under ``sidecar.integrity.crc_mismatch`` — the
Thallus posture: transport corruption must be an error, never rows.

Cross-process TCP exchange (ISSUE 6): the in-mesh collective above
remains the fast path WITHIN one runtime; ``TcpExchange`` adds the
cross-PROCESS mode — two single-host runtimes exchanging hash
partitions as versioned columnar frames (columnar/frames.py, the same
codec sidecar wire payloads and memgov spills use) over plain TCP
sockets. Pull-based: each peer serves its published partitions, so the
deadline/retry/breaker/CRC machinery rides the FETCH side unchanged —
a tampered frame decodes to retryable ``DataCorruption`` and the retry
re-fetches; a crashed peer is a connection fault the retry outlives
(supervisors respawn peers; published partitions are recomputed
deterministically). ``SRJT_EXCHANGE_MODE`` (default ``mesh``) is the
transport selector for callers that host a cross-process rank — the
exchange-worker harness and benchmarks consult ``exchange_mode()``;
the in-library collectives (``exchange_by_key`` etc.) always use the
mesh and ignore it. Peers are addressed ``rank=host:port``. The
two-process
harness behind ``python -m spark_rapids_jni_tpu.parallel.shuffle
--exchange-worker`` drives the distributed-groupby acceptance test and
``benchmarks/bench_pool.py``'s exchange MB/s row.
"""

from __future__ import annotations

import os
import socket as socket_mod
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..columnar import Table
from ..columnar.dtype import TypeId
from ..ops.hashing import hash_partition_map
from ..ops.copying import gather
from ..utils.dispatch import op_boundary
from ._smcache import cached_sm, shard_map

__all__ = [
    "hash_partition",
    "all_to_all_exchange",
    "exchange_by_key",
    "exchange_mode",
    "TcpExchange",
    "exchange_breaker",
    "spawn_exchange_peer",
]


@op_boundary("hash_partition")
def hash_partition(table: Table, num_partitions: int, key_cols: Sequence[str]) -> Tuple[Table, List[int]]:
    """Single-device cudf-style hash_partition: rows reordered so each
    partition is contiguous; returns (table, partition start offsets)."""
    pmap = hash_partition_map([table.column(c) for c in key_cols], num_partitions)
    order = jnp.argsort(pmap, stable=True).astype(jnp.int32)
    out = gather(table, order)
    counts = np.bincount(np.asarray(pmap), minlength=num_partitions)
    offsets = np.concatenate([[0], np.cumsum(counts)])[:-1].tolist()
    return out, offsets


def _exchange_checksum(arrays) -> int:
    """Order-independent payload checksum for the all-to-all (ISSUE 5,
    utils/integrity.py): the exchange PERMUTES rows across shards, so a
    positional CRC cannot survive it — the invariant is the byte
    MULTISET, summarized as the wraparound-u64 sum of every lane's bit
    pattern. Unoccupied bucket slots are zero-initialized and add
    nothing, so the sum over the capacity-padded receive buffers equals
    the sum over the dense send payload exactly when every row landed
    intact. Computed on device (one reduction per array), no host copy."""
    from jax import lax as _lax

    total = 0
    for a in arrays:
        if a.dtype == jnp.bool_:
            v = a.astype(jnp.uint8)
        else:
            v = _lax.bitcast_convert_type(
                a, jnp.dtype(f"uint{a.dtype.itemsize * 8}")
            )
        total = (total + int(jnp.sum(v.astype(jnp.uint64)))) & 0xFFFFFFFFFFFFFFFF
    return total


def _bucketize(vals: jnp.ndarray, dest: jnp.ndarray, n_parts: int, capacity: int):
    """Per-shard scatter of [n] rows into [P, capacity] buckets.

    Returns (buckets, mask, overflow). Rows beyond capacity for their
    destination are dropped and flagged.
    """
    n = dest.shape[0]
    order = jnp.argsort(dest)  # group rows by destination
    d_sorted = dest[order]
    # position within destination bucket: index along the sorted run
    idx = jnp.arange(n, dtype=jnp.int32)
    run_start = jnp.searchsorted(d_sorted, jnp.arange(n_parts, dtype=jnp.int32), side="left")
    slot = idx - run_start[d_sorted]
    overflow = jnp.any(slot >= capacity)
    keep = slot < capacity
    # overflowing rows scatter out of range and are dropped (mode="drop"),
    # never aliasing the legitimate occupant of the last slot
    flat = jnp.where(keep, d_sorted.astype(jnp.int32) * capacity + slot, n_parts * capacity)

    shape = (n_parts * capacity,) + vals.shape[1:]
    buckets = jnp.zeros(shape, vals.dtype)
    buckets = buckets.at[flat].set(vals[order], mode="drop")
    mask = jnp.zeros((n_parts * capacity,), bool).at[flat].set(True, mode="drop")
    return (
        buckets.reshape((n_parts, capacity) + vals.shape[1:]),
        mask.reshape(n_parts, capacity),
        overflow,
    )


def _exchange_once(arrays, dest, mesh: Mesh, axis: str, capacity: int, n_parts: int):
    """One all-to-all execution at a fixed capacity."""

    def body(dest_local, *arrs):
        outs = []
        ovf = jnp.zeros((), bool)
        mask = None
        for a in arrs:
            b, m, o = _bucketize(a, dest_local, n_parts, capacity)
            # all_to_all: split axis 0 (destinations), concat received
            r = lax.all_to_all(b, axis, split_axis=0, concat_axis=0, tiled=True)
            outs.append(r)
            ovf = ovf | o
            mask = m
        rm = lax.all_to_all(mask, axis, split_axis=0, concat_axis=0, tiled=True)
        return tuple(outs) + (rm, ovf[None])

    spec = P(axis)
    in_specs = (spec,) + tuple(spec for _ in arrays)
    out_specs = tuple(spec for _ in arrays) + (spec, spec)
    f = cached_sm(
        ("a2a_exchange", mesh, axis, int(capacity), len(arrays),
         tuple(str(a.dtype) for a in arrays)),
        lambda: jax.jit(shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs)),
    )
    *received, recv_mask, overflow = f(dest, *arrays)
    return received, recv_mask, overflow


@op_boundary("all_to_all_exchange")
def all_to_all_exchange(
    arrays: Sequence[jnp.ndarray],
    dest: jnp.ndarray,
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    on_overflow: str = "raise",
):
    """Exchange row-sharded arrays so row i lands on shard dest[i].

    arrays: row-sharded along `axis` ([N_global, ...] each); dest:
    [N_global] int32 in [0, mesh axis size). Returns (received_arrays,
    recv_mask, overflow): received arrays are [P * capacity * ...] per
    shard, i.e. globally [N_shards, P, capacity, ...] flattened on the
    leading axis, with recv_mask marking occupied slots.

    Overflow semantics (VERDICT r3 item 8): a caller-supplied capacity
    that a skewed destination exceeds can NOT silently hand back
    truncated data. ``on_overflow="raise"`` (default) raises
    ``RetryableError`` — the Spark task-retry class; capacity-managing
    callers (the Table tier recomputes and retries) opt into the
    flag-only contract with ``on_overflow="flag"``; and
    ``on_overflow="retry"`` closes the loop IN-OP: the exchange doubles
    the capacity (geometric, bounded by the per-shard ceiling that
    cannot overflow) and re-executes until every row lands — the UCX
    shuffle transient-failure posture, wired through the retry
    orchestrator's counters (utils/retry.py). The defaulted capacity
    (= rows per shard) cannot overflow.
    """
    if on_overflow not in ("raise", "flag", "retry"):
        raise ValueError(
            f"on_overflow must be 'raise', 'flag', or 'retry', got {on_overflow!r}"
        )
    if capacity is not None and capacity < 1:
        # capacity=0 would make the geometric escalation a fixed point
        # (2*0 == 0): the retry loop must always be able to grow
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    n_parts = mesh.shape[axis]
    n_global = dest.shape[0]
    per_shard = n_global // n_parts
    if capacity is None:
        capacity = per_shard  # safe: one shard can absorb everything

    from .. import memgov
    from ..utils import integrity, metrics

    armed = metrics.is_enabled()
    governed = on_overflow == "retry" and memgov.is_enabled()
    # integrity (ISSUE 5): checksum the payload entering the collective
    # so a corrupted/dropped row surfaces as retryable DataCorruption
    # (op_boundary's armed retry re-executes), never as wrong rows
    checked = integrity.is_enabled()
    sent_sum = _exchange_checksum(arrays) if checked else None
    # per-GLOBAL-ROW wire cost: the collective moves capacity-padded
    # [n_parts, capacity] buckets per shard per array (NOT the dense
    # row payload) plus the 1-byte/slot occupancy mask — the padded
    # footprint is what a GB/s artifact must divide by, and it changes
    # each time the escalation loop doubles capacity. ONE cost model:
    # the metrics wire accounting and the governor's escalation
    # estimate read the same number
    row_bytes = (
        sum(int(a.nbytes) // max(a.shape[0], 1) for a in arrays) + 1
        if armed or governed else 0
    )
    t0 = time.perf_counter() if armed else 0.0
    wire_bytes = 0
    while True:
        received, recv_mask, overflow = _exchange_once(
            arrays, dest, mesh, axis, int(capacity), n_parts
        )
        if armed:
            # bytes THIS execution put on the wire (failed-overflow
            # attempts moved their buckets too, so accumulate per try)
            attempt_bytes = n_parts * n_parts * int(capacity) * row_bytes
            wire_bytes += attempt_bytes
            metrics.counter("shuffle.bytes_exchanged").inc(attempt_bytes)
        overflowed = bool(np.asarray(overflow).any())
        if not overflowed or on_overflow == "flag":
            if checked and not overflowed:
                # verify only complete exchanges: a flagged overflow
                # legitimately dropped rows, which is the CALLER's
                # recompute contract, not corruption
                from ..utils import metrics as _m

                _m.registry().counter("sidecar.integrity.exchanges_checked").inc()
                recv_sum = _exchange_checksum(received)
                recv_rows = int(jnp.sum(recv_mask.astype(jnp.uint64)))
                if recv_sum != sent_sum or recv_rows != int(n_global):
                    raise integrity.raise_corruption(
                        "shuffle.exchange",
                        f"sent 0x{sent_sum:016x}/{int(n_global)} rows != "
                        f"recv 0x{recv_sum:016x}/{recv_rows} rows",
                    )
            if armed:
                elapsed = time.perf_counter() - t0
                metrics.counter("shuffle.exchanges").inc()
                metrics.histogram("shuffle.exchange_us").record(elapsed * 1e6)
                metrics.event(
                    "shuffle.exchange", axis=axis, n_parts=n_parts,
                    capacity=int(capacity), wire_bytes=wire_bytes,
                    wall_us=round(elapsed * 1e6, 1),
                    overflow=overflowed,
                )
            return received, recv_mask, overflow
        if on_overflow == "retry" and capacity < per_shard:
            # the capacity re-try loop consults the deadline/cancel
            # token BETWEEN attempts (utils/deadline.py): an escalated
            # re-execution never starts once the query budget is gone
            from ..utils import deadline as deadline_mod

            deadline_mod.check("all_to_all_exchange.capacity_retry")
            # geometric escalation: at most ceil(log2(per_shard/cap0))
            # re-executions before the cannot-overflow ceiling
            new_capacity = min(2 * int(capacity), per_shard)
            # memory governor (memgov/, ISSUE 4): the doubled bucket
            # matrices are a footprint the op's original admission never
            # covered — route the escalated estimate through the
            # controller (which GROWS the held admission on success) so
            # a doubling that cannot fit spills cold catalog buffers or
            # raises the retryable MemoryBudgetExceeded (the split
            # path), never an XLA OOM
            if governed:
                from ..utils.memory import exchange_bytes_estimate

                memgov.ensure_fits(
                    exchange_bytes_estimate(
                        row_bytes, n_parts, int(new_capacity)
                    ),
                    "all_to_all_exchange.capacity_retry",
                )
            metrics.event(
                "shuffle.capacity_escalation", axis=axis,
                capacity=int(capacity), new_capacity=int(new_capacity),
            )
            capacity = new_capacity
            from ..utils import retry as retry_mod

            retry_mod.record_capacity_retry()
            continue
        from ..utils.errors import RetryableError

        raise RetryableError(
            f"all_to_all_exchange: a destination shard received more than "
            f"capacity={capacity} rows; retry with a larger capacity "
            f"(rows would otherwise be dropped)"
        )


@op_boundary("exchange_by_key")
def exchange_by_key(
    table: Table,
    key_cols: Sequence[str],
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    on_overflow: str = "raise",
):
    """Hash-repartition a row-sharded fixed-width Table over the mesh.

    Returns (pairs_by_column, recv_mask, overflow) where each pair is
    (data, validity-or-None) — null masks travel with their column so
    null rows stay null on the receiving shard. Rows of one key all land
    on the same shard (hash pmod, ops/hashing parity with the
    single-device partitioner).

    ``on_overflow="retry"`` makes a capacity overflow self-healing: the
    exchange doubles ``capacity`` (geometric, bounded by the per-shard
    ceiling) and re-executes the all-to-all instead of raising — the
    shuffle-side half of the retry orchestrator (utils/retry.py).
    """
    if on_overflow not in ("raise", "flag", "retry"):
        raise ValueError(
            f"on_overflow must be 'raise', 'flag', or 'retry', got {on_overflow!r}"
        )
    for c in table.columns:
        if c.dtype.id in (TypeId.STRING, TypeId.LIST):
            raise ValueError(
                "exchange_by_key moves fixed-width payloads; use "
                "parallel.table_ops.exchange_table, which dictionary-encodes "
                "string columns automatically"
            )
    dest = hash_partition_map([table.column(c) for c in key_cols], mesh.shape[axis])
    arrays: List[jnp.ndarray] = []
    has_validity: List[bool] = []
    for c in table.columns:
        arrays.append(c.data)
        has_validity.append(c.validity is not None)
        if c.validity is not None:
            arrays.append(c.validity)
    received, recv_mask, overflow = all_to_all_exchange(
        arrays, dest.astype(jnp.int32), mesh, axis, capacity, on_overflow=on_overflow
    )
    pairs = []
    it = iter(received)
    for nullable in has_validity:
        data = next(it)
        pairs.append((data, next(it) if nullable else None))
    return pairs, recv_mask, overflow


# ---------------------------------------------------------------------------
# cross-process TCP exchange (ISSUE 6): hash partitions as columnar
# frames between two single-host runtimes, pull-based so deadline +
# retry + breaker + CRC ride the fetch side unchanged
# ---------------------------------------------------------------------------

_EXC_MAGIC = b"SRJTEXC1"
_EXC_REQ = struct.Struct("<8sIII")  # magic, verb, epoch, part
_EXC_RESP = struct.Struct("<IQ")  # status, payload length
_EXC_GET = 1
# srjt-trace (ISSUE 12): GET whose request carries a 17-byte trace
# context (utils/tracing.wire_context) right after the header — the
# serving peer's span parents to the fetcher's span across the process
# boundary. Negotiated per request: untraced peers keep verb 1
# byte-for-byte.
_EXC_GET_TRACED = 3
# srjt-cluster (ISSUE 16): epoch-fenced GETs carry the requester's
# 4-byte cluster generation right after the header (after the trace
# blob on the traced variant). The serving peer answers _EXC_STALE on
# any mismatch — in either direction: a zombie server (older gen) must
# not serve bytes to a current client, and a zombie CLIENT (older gen)
# must not be fed partitions it will attribute to a dead world view.
# A fenced OK response prefixes the SERVER's 4-byte generation before
# the frame, so the fetcher verifies it before a single payload byte
# reaches the decoder.
_EXC_GET_FENCED = 4
_EXC_GET_FENCED_TRACED = 5
# liveness probe (parallel/cluster.py heartbeats): request epoch field
# carries the sender's generation, part field the sender's rank; the
# response is _EXC_OK with a 4-byte payload = responder's generation.
_EXC_PING = 6
_EXC_GEN = struct.Struct("<I")  # the 4-byte generation blob
_EXC_OK = 0
_EXC_RETRY = 1  # partition not (yet) published here: retryable
_EXC_ERR = 2
_EXC_STALE = 3  # generation fence mismatch: retryable desync

# epoch-namespace strides (ISSUE 16): the binary-tree exchange keys each
# round's intermediate frames at ``epoch + (round+1) * _TREE_EPOCH_STRIDE``
# and a recovery republish lands at ``epoch + (dead_rank+1) *
# _RECOVERY_EPOCH_STRIDE`` — both far above any caller's base-epoch
# sequence (queries count epochs from 0 upward), so derived keys never
# collide with a real round or with each other.
_TREE_EPOCH_STRIDE = 1 << 16
_RECOVERY_EPOCH_STRIDE = 1 << 24


def exchange_mode() -> str:
    """``SRJT_EXCHANGE_MODE``: ``mesh`` (default — the in-process
    ``lax.all_to_all`` fast path) or ``tcp`` (cross-process
    ``TcpExchange`` framing). Consulted by callers that choose a
    transport — the ``--exchange-worker`` harness and benchmarks; the
    in-library mesh collectives always use the collective and do not
    read this knob."""
    from ..utils import knobs

    # the typed accessor warns and keeps "mesh" on an unknown value
    return knobs.get_str("SRJT_EXCHANGE_MODE")


_EXC_BREAKERS: Dict[str, object] = {}
_EXC_BREAKER_LOCK = threading.Lock()


class _AllExchangeBreakers:
    """No-arg ``exchange_breaker()`` facade: operations fan out to
    every per-peer breaker (tests and teardown paths reset the whole
    exchange path in one call, exactly like the old process-global
    breaker)."""

    @staticmethod
    def _all():
        with _EXC_BREAKER_LOCK:
            return list(_EXC_BREAKERS.values())

    def reset(self) -> None:
        for br in self._all():
            br.reset()

    def snapshot(self) -> Dict[str, dict]:
        with _EXC_BREAKER_LOCK:
            return {addr: br.snapshot() for addr, br in _EXC_BREAKERS.items()}


def exchange_breaker(addr: Optional[str] = None):
    """Breaker for the TCP exchange path (mirrors sidecar.breaker()),
    PER-PEER (ISSUE 16): each peer address owns its own breaker, so a
    dead rank fast-fails its own fetches while pulls from healthy
    peers flow untouched — one dark peer must never dark the whole
    exchange. Consecutive fetch failures open a peer's breaker and
    further fetches to it fast-fail retryably without paying a dial; a
    half-open probe after the cooldown restores the path. States land
    under ``shuffle.exchange.breaker.<peer>.*``.

    With no ``addr`` the returned facade fans out to every per-peer
    breaker (``reset()`` / ``snapshot()`` — the teardown surface)."""
    if addr is None:
        return _AllExchangeBreakers()
    with _EXC_BREAKER_LOCK:
        br = _EXC_BREAKERS.get(addr)
        if br is None:
            from ..utils.deadline import CircuitBreaker

            # metric-name-safe peer key: dots and colons would collide
            # with the metrics namespace separators
            peer = addr.replace(".", "-").replace(":", "_")
            br = CircuitBreaker(f"shuffle.exchange.breaker.{peer}")
            _EXC_BREAKERS[addr] = br
        return br


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def _recv_exact_tcp(sock, n: int, deadline: float) -> bytes:
    """Read exactly n bytes under a whole-request deadline (the
    SupervisedClient._recv_deadline discipline: the socket timeout
    shrinks to the remaining budget each iteration)."""
    buf = bytearray()
    while len(buf) < n:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket_mod.timeout("exchange deadline exhausted")
        sock.settimeout(remaining)
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("exchange: peer closed")
        buf.extend(chunk)
    return bytes(buf)


class TcpExchange:
    """One runtime's end of the cross-process exchange: a server that
    publishes this rank's outgoing partitions (encoded once as
    columnar frames) and a fetch client that pulls this rank's
    incoming partitions from peers under deadline + retry + breaker +
    CRC.

    Keys are ``(epoch, part)`` — an epoch is one exchange round (query
    stage), ``part`` the destination rank. A fetch for a partition not
    yet published parks on a condition server-side (bounded) and then
    answers retryably, so peer startup races cost latency, never
    wrong answers. Chaos hooks: each served request crosses
    ``faultinj.maybe_inject("exchange.serve")`` (``crash``/``delay``
    kinds) and each response frame crosses
    ``faultinj.maybe_corrupt("exchange.frame", ...)`` AFTER encoding —
    exactly like a transport flipping bits under the CRC, which the
    decoder must catch."""

    def __init__(self, rank: int, bind: str = "127.0.0.1:0",
                 deadline_s: Optional[float] = None,
                 publish_wait_s: float = 10.0,
                 retain_epochs: Optional[int] = None):
        from ..utils import knobs

        self.rank = int(rank)
        if deadline_s is None:
            deadline_s = knobs.get_float("SRJT_EXCHANGE_TIMEOUT_SEC")
        self.deadline_s = float(deadline_s)
        self.publish_wait_s = float(publish_wait_s)
        if retain_epochs is None:
            retain_epochs = knobs.get_int("SRJT_EXCHANGE_RETAIN_EPOCHS")
        # publish() evicts everything older than the newest
        # `retain_epochs` distinct epochs: a long-lived runtime doing
        # one exchange round per query stage must not accumulate every
        # encoded partition forever, while a crashed peer's
        # respawn-republish window (the previous few epochs) stays
        # servable
        self.retain_epochs = max(int(retain_epochs), 1)
        self._frames: Dict[Tuple[int, int], bytes] = {}
        self._lock = threading.Lock()
        self._published = threading.Condition(self._lock)
        self._closed = False
        # srjt-cluster (ISSUE 16): the epoch fence. None = unfenced
        # (the pre-cluster wire protocol, byte-for-byte); an attached
        # ClusterView keeps this equal to its membership generation, so
        # every fetch carries it and every served GET enforces it.
        self._generation: Optional[int] = None
        host, port = _parse_addr(bind)
        self._srv = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
        self._srv.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.address = "%s:%d" % self._srv.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"srjt-exchange-r{self.rank}",
        )
        self._accept_thread.start()

    # -- the epoch fence (ISSUE 16) ------------------------------------------

    def set_generation(self, generation: Optional[int]) -> None:
        """Install the cluster membership generation this exchange
        serves and fetches under (None disarms the fence). The
        ClusterView calls this on attach and on every bump — a
        republish after a member death is served under the NEW
        generation, and any peer still fetching under the old one is
        answered ``_EXC_STALE`` instead of bytes."""
        with self._lock:
            self._generation = None if generation is None else int(generation)

    def generation(self) -> Optional[int]:
        with self._lock:
            return self._generation

    # -- server side ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # closed
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn) -> None:
        from ..utils import tracing

        try:
            conn.settimeout(self.deadline_s)
            while True:
                try:
                    hdr = b""
                    while len(hdr) < _EXC_REQ.size:
                        chunk = conn.recv(_EXC_REQ.size - len(hdr))
                        if not chunk:
                            return
                        hdr += chunk
                except (OSError, socket_mod.timeout):
                    return
                magic, verb, epoch, part = _EXC_REQ.unpack(hdr)
                if magic != _EXC_MAGIC or verb not in (
                    _EXC_GET, _EXC_GET_TRACED,
                    _EXC_GET_FENCED, _EXC_GET_FENCED_TRACED,
                    _EXC_PING,
                ):
                    conn.sendall(_EXC_RESP.pack(_EXC_ERR, 0))
                    return
                if verb == _EXC_PING:
                    # liveness probe (ISSUE 16): epoch field = sender
                    # generation, part = sender rank (observability
                    # only — a PING never gates on the fence; the
                    # RESPONSE carries our generation so the prober
                    # learns about a bump it missed)
                    own = self.generation()
                    conn.sendall(
                        _EXC_RESP.pack(_EXC_OK, _EXC_GEN.size)
                        + _EXC_GEN.pack(own or 0)
                    )
                    continue
                # srjt-trace (ISSUE 12): a traced GET carries the
                # 17-byte context right after the header — read it
                # unconditionally so the stream stays framed even when
                # tracing is disarmed on this side
                tctx = None
                if verb in (_EXC_GET_TRACED, _EXC_GET_FENCED_TRACED):
                    try:
                        tb = b""
                        while len(tb) < tracing.TRACE_CTX_LEN:
                            chunk = conn.recv(tracing.TRACE_CTX_LEN - len(tb))
                            if not chunk:
                                return
                            tb += chunk
                    except (OSError, socket_mod.timeout):
                        return
                    tctx = tracing.decode_wire_context(tb)
                # srjt-cluster (ISSUE 16): a fenced GET carries the
                # requester's 4-byte generation after the header (and
                # trace blob) — read it unconditionally, framing first
                req_gen = None
                if verb in (_EXC_GET_FENCED, _EXC_GET_FENCED_TRACED):
                    try:
                        gb = b""
                        while len(gb) < _EXC_GEN.size:
                            chunk = conn.recv(_EXC_GEN.size - len(gb))
                            if not chunk:
                                return
                            gb += chunk
                    except (OSError, socket_mod.timeout):
                        return
                    (req_gen,) = _EXC_GEN.unpack(gb)
                if tctx is not None and tracing.is_enabled():
                    # the serving peer's half of the cross-process
                    # trace: the wait-for-publish and the frame send
                    # parent to the fetcher's span, logged HERE
                    with tracing.remote_scope(*tctx):
                        with tracing.span(
                            "exchange.serve", epoch=int(epoch),
                            part=int(part), rank=self.rank,
                        ):
                            self._answer_get(conn, epoch, part, req_gen)
                else:
                    self._answer_get(conn, epoch, part, req_gen)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _answer_get(self, conn, epoch: int, part: int,
                    req_gen: Optional[int] = None) -> None:
        """Answer one GET: enforce the epoch fence, wait (bounded) for
        the partition to publish, then send it — or a retryable
        not-yet-published / stale-generation status."""
        from ..utils import faultinj, metrics

        # chaos choke point: `crash` kills the serving process
        # mid-request (the peer sees a dead transport and
        # retries), `delay` models a slow peer
        if faultinj.is_enabled():
            faultinj.maybe_inject("exchange.serve")
        own = self.generation()
        if req_gen is not None and (own is None or own != req_gen):
            # fence mismatch in EITHER direction: a zombie server must
            # not feed a current client, and a zombie client must not
            # be fed — the answer carries our generation so the
            # requester can resynchronize, and zero payload bytes flow
            metrics.registry().counter(
                "cluster.stale_generation_refused"
            ).inc()
            conn.sendall(
                _EXC_RESP.pack(_EXC_STALE, _EXC_GEN.size)
                + _EXC_GEN.pack(own or 0)
            )
            return
        with self._published:
            end = time.monotonic() + self.publish_wait_s
            blob = self._frames.get((epoch, part))
            while blob is None and not self._closed:
                left = end - time.monotonic()
                if left <= 0:
                    break
                self._published.wait(left)
                blob = self._frames.get((epoch, part))
        if blob is None:
            conn.sendall(_EXC_RESP.pack(_EXC_RETRY, 0))
            return
        wire = blob
        if faultinj.is_enabled():
            # flips bytes AFTER the frame (and its CRCs) was
            # encoded — the fetcher's decode MUST catch it
            wire = faultinj.maybe_corrupt("exchange.frame", blob)
        # a fenced OK prefixes the server generation so the fetcher
        # verifies it BEFORE any payload byte reaches the decoder
        prefix = b"" if req_gen is None else _EXC_GEN.pack(own)
        header = _EXC_RESP.pack(_EXC_OK, len(prefix) + len(wire)) + prefix
        if faultinj.is_enabled():
            # split the response at the header/payload seam so a
            # `crash` rule keyed exchange.serve.payload kills this
            # process exactly between the two writes — the
            # died-mid-frame chaos the fetch side must classify as
            # retryable UNAVAILABLE, never DataCorruption. Production
            # (injector disabled) keeps the single-write path.
            conn.sendall(header)
            faultinj.maybe_inject("exchange.serve.payload")
            conn.sendall(wire)
        else:
            conn.sendall(header + wire)
        metrics.counter("shuffle.tcp.bytes_out").inc(len(wire))

    def publish(self, epoch: int, partitions: Dict[int, "Table"]) -> None:
        """Encode and expose this rank's outgoing partitions for
        ``epoch`` (one frame per destination rank, per-column CRC under
        the integrity gate). Idempotent per key — a respawned peer
        re-publishing identical deterministic partitions is a no-op."""
        from ..columnar import frames as frames_mod
        from ..utils import metrics

        encoded = {
            (int(epoch), int(part)): frames_mod.encode_table(t)
            for part, t in partitions.items()
        }
        evicted = 0
        with self._published:
            self._frames.update(encoded)
            epochs = sorted({e for e, _ in self._frames})
            for old in epochs[: max(len(epochs) - self.retain_epochs, 0)]:
                stale = [k for k in self._frames if k[0] == old]
                for k in stale:
                    del self._frames[k]
                evicted += len(stale)
            self._published.notify_all()
        metrics.counter("shuffle.tcp.published").inc(len(encoded))
        if evicted:
            metrics.counter("shuffle.tcp.frames_evicted").inc(evicted)

    def drop_epoch(self, epoch: int) -> int:
        """Release one exchange round's published frames (e.g. after
        every peer has fetched); returns the number dropped."""
        with self._published:
            stale = [k for k in self._frames if k[0] == int(epoch)]
            for k in stale:
                del self._frames[k]
        return len(stale)

    # -- fetch side ----------------------------------------------------------

    def _fetch_once(self, addr: str, epoch: int, part: int) -> "Table":
        """One fetch attempt — the unit the retry orchestrator re-runs.
        Transport faults and not-yet-published answers raise
        RetryableError; a frame whose bytes rotted raises retryable
        DataCorruption from the decoder; an exhausted query budget
        raises DeadlineExceeded (never a raw socket timeout)."""
        from ..columnar import frames as frames_mod
        from ..utils import deadline as deadline_mod, metrics
        from ..utils.errors import RetryableError

        d = deadline_mod.current()
        # adaptive fetch deadline (ISSUE 9): observed q99 × multiplier
        # once warm, clamped into [floor, SRJT_EXCHANGE_TIMEOUT_SEC] —
        # a hung peer is detected at straggler timescales, not the
        # static knob's; the query budget still clamps below
        budget_s, clamped = metrics.adaptive_timeout_s(
            "shuffle.tcp.fetch_lat_us", self.deadline_s
        )
        if clamped:
            metrics.registry().counter(
                "shuffle.tcp.adaptive_timeout_clamps"
            ).inc()
        if d is not None:
            d.check("tcp_exchange_fetch")
            budget_s = min(budget_s, max(d.remaining(), 1e-3))
        deadline = time.monotonic() + budget_s
        t0 = time.monotonic()
        lat_hist = metrics.registry().histogram("shuffle.tcp.fetch_lat_us")
        host, port = _parse_addr(addr)
        s = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
        # the epoch fence (ISSUE 16): fenced verbs whenever a cluster
        # generation is installed; the request carries it and the OK
        # response must echo the server's — verified below before any
        # byte reaches the decoder
        gen = self.generation()
        phase = "connect"
        try:
            s.settimeout(budget_s)
            # srjt-trace (ISSUE 12): a sampled active context rides the
            # request as the traced GET verb + 17-byte blob, so the
            # peer's serve span parents to this fetch across processes
            from ..utils import faultinj, tracing

            tblob = tracing.wire_context()
            if gen is None:
                verb = _EXC_GET if tblob is None else _EXC_GET_TRACED
                gblob = b""
            else:
                verb = (_EXC_GET_FENCED if tblob is None
                        else _EXC_GET_FENCED_TRACED)
                gblob = _EXC_GEN.pack(gen)
            try:
                # netsplit chaos choke point (ISSUE 16): a `netsplit`
                # rule keyed exchange.connect (optionally @r<N>) raises
                # ConnectionRefusedError HERE, inside the handler that
                # classifies real refused connects — the partitioned
                # path is byte-for-byte the production path
                if faultinj.is_enabled():
                    faultinj.maybe_inject("exchange.connect")
                s.connect((host, port))
                s.sendall(
                    _EXC_REQ.pack(_EXC_MAGIC, verb, epoch, part)
                    + (tblob or b"")
                    + gblob
                )
                phase = "header"
                status, blen = _EXC_RESP.unpack(
                    _recv_exact_tcp(s, _EXC_RESP.size, deadline)
                )
                phase = "payload"
                blob = _recv_exact_tcp(s, blen, deadline) if blen else b""
            except socket_mod.timeout as e:
                # record the timed-out elapsed as a latency sample so
                # an over-tight adaptive clamp self-corrects upward
                lat_hist.record((time.monotonic() - t0) * 1e6)
                if d is not None and d.done():
                    raise d.exceeded("tcp exchange fetch") from e
                raise RetryableError(
                    f"shuffle exchange: DEADLINE_EXCEEDED: fetch of "
                    f"(epoch {epoch}, part {part}) from {addr} exceeded "
                    f"{budget_s:g}s"
                ) from e
            except (ConnectionError, OSError) as e:
                # a peer that died mid-frame (reset/EOF before or while
                # framing the header or payload) is UNAVAILABLE — the
                # recovery path's signal, explicitly NOT the corruption
                # path: no frame was accepted, so there is nothing for
                # a CRC to vouch for (ISSUE 16 satellite)
                raise RetryableError(
                    f"shuffle exchange: UNAVAILABLE: peer {addr} reset "
                    f"before completing frame ({phase}: {e})"
                ) from e
        finally:
            s.close()
        if status == _EXC_STALE:
            # generation fence tripped: the peer lives in a different
            # membership epoch (we are stale, or it is a zombie). Zero
            # payload bytes were accepted; retryable desync — the
            # retry re-reads the installed generation, so a bumped
            # fence heals the next attempt.
            peer_gen = _EXC_GEN.unpack(blob)[0] if blob else 0
            metrics.registry().counter(
                "cluster.stale_generation_rejects"
            ).inc()
            raise RetryableError(
                f"shuffle exchange: DESYNC: generation fence mismatch "
                f"with peer {addr} (ours {gen}, peer {peer_gen}) for "
                f"(epoch {epoch}, part {part})"
            )
        if status == _EXC_RETRY:
            raise RetryableError(
                f"shuffle exchange: UNAVAILABLE: peer {addr} has not "
                f"published (epoch {epoch}, part {part}) yet"
            )
        if status != _EXC_OK:
            # _EXC_ERR means the peer rejected our magic/verb: a
            # misaddressed or version-skewed peer, deterministic on
            # every attempt — fail fast instead of burning the whole
            # retry budget on a config error (the transient cases are
            # _EXC_RETRY and the transport faults above)
            from ..utils.errors import FatalDeviceError

            raise FatalDeviceError(
                f"shuffle exchange: peer {addr} answered error status "
                f"{status} (protocol mismatch — wrong service or "
                "version-skewed peer?)"
            )
        if gen is not None:
            # the fenced OK prefixes the SERVER's generation: verify it
            # against ours before a single payload byte reaches the
            # decoder — a zombie peer's bytes are rejected here, and
            # the accept counter below stays zero by construction (the
            # chaos artifact gate asserts exactly that)
            if len(blob) < _EXC_GEN.size:
                raise RetryableError(
                    f"shuffle exchange: UNAVAILABLE: peer {addr} reset "
                    f"before completing frame (fence prefix truncated)"
                )
            (srv_gen,) = _EXC_GEN.unpack(blob[:_EXC_GEN.size])
            if srv_gen != gen:
                metrics.registry().counter(
                    "cluster.stale_generation_rejects"
                ).inc()
                raise RetryableError(
                    f"shuffle exchange: DESYNC: peer {addr} answered "
                    f"under generation {srv_gen}, ours is {gen} — "
                    f"stale bytes rejected undecoded"
                )
            blob = blob[_EXC_GEN.size:]
        lat_hist.record((time.monotonic() - t0) * 1e6)
        metrics.counter("shuffle.tcp.bytes_in").inc(len(blob))
        # decode verifies the frame header + every column CRC: a
        # tampered exchange is retryable DataCorruption, never rows
        return frames_mod.decode_table(blob, where="shuffle.exchange")

    def fetch(self, addr: str, epoch: int, part: int) -> "Table":
        """Pull one partition from ``addr`` under retry + breaker +
        deadline. Corruption and transport faults retry; exhaustion
        records a breaker failure and re-raises retryably (the caller's
        supervisor may respawn the peer and call again).

        srjt-trace (ISSUE 12): one ``exchange.fetch`` span per fetch
        covers every retry attempt; each attempt propagates the
        context to the serving peer (``_fetch_once``)."""
        from ..utils import tracing

        with tracing.span(
            "exchange.fetch", peer=addr, epoch=int(epoch), part=int(part)
        ):
            return self._fetch_impl(addr, epoch, part)

    def _fetch_impl(self, addr: str, epoch: int, part: int) -> "Table":
        from ..utils import metrics, retry
        from ..utils.errors import DeadlineExceeded, RetryableError

        br = exchange_breaker(addr)
        if not br.allow():
            raise RetryableError(
                "shuffle exchange: UNAVAILABLE: exchange breaker open "
                f"(peer {addr})"
            )
        t0 = time.perf_counter()
        try:
            table = retry.call_with_retry(
                self._fetch_once, addr, epoch, part,
                op_name="tcp_exchange_fetch",
            )
        except DeadlineExceeded:
            br.record_failure(cause="deadline")
            raise
        except RetryableError:
            br.record_failure(cause="unavailable")
            raise
        except BaseException:
            br.abort_probe()
            raise
        br.record_success()
        metrics.counter("shuffle.tcp.fetches").inc()
        metrics.histogram("shuffle.tcp.fetch_us").record(
            (time.perf_counter() - t0) * 1e6
        )
        return table

    def ping(self, addr: str, timeout_s: float) -> int:
        """One liveness probe (ISSUE 16): PING ``addr`` and return the
        responder's cluster generation (0 = unfenced). Raises on ANY
        transport fault — the heartbeat loop counts every raise as one
        miss; classification beyond alive/not-alive is the
        ClusterView's job, not the probe's. Runs outside the breaker
        and retry orchestrator on purpose: a probe must measure the
        peer, not the recovery machinery."""
        from ..utils import faultinj
        from ..utils.errors import RetryableError

        host, port = _parse_addr(addr)
        deadline = time.monotonic() + max(float(timeout_s), 1e-3)
        gen = self.generation()
        s = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
        try:
            s.settimeout(max(float(timeout_s), 1e-3))
            if faultinj.is_enabled():
                # the same netsplit choke point the fetch path crosses:
                # a partitioned rank's heartbeats fail exactly like its
                # fetches do
                faultinj.maybe_inject("exchange.connect")
            s.connect((host, port))
            s.sendall(_EXC_REQ.pack(_EXC_MAGIC, _EXC_PING, gen or 0, self.rank))
            status, blen = _EXC_RESP.unpack(
                _recv_exact_tcp(s, _EXC_RESP.size, deadline)
            )
            blob = _recv_exact_tcp(s, blen, deadline) if blen else b""
        finally:
            s.close()
        if status != _EXC_OK or len(blob) < _EXC_GEN.size:
            raise RetryableError(
                f"shuffle exchange: UNAVAILABLE: malformed PING answer "
                f"from {addr} (status {status})"
            )
        return _EXC_GEN.unpack(blob[:_EXC_GEN.size])[0]

    # -- the one-call partition exchange -------------------------------------

    def exchange_table(self, table: "Table", key_cols: Sequence[str],
                       peers: Dict[int, str], epoch: int = 0,
                       topology: Optional[str] = None,
                       cluster=None) -> "Table":
        """Hash-repartition ``table`` across this rank and ``peers``
        (rank -> "host:port", this rank excluded): rows of one key all
        land on hash(key) % world, whatever process they started in.
        Returns this rank's incoming partition with a deterministic row
        order, so downstream aggregation is reproducible bit for bit.

        ``topology`` picks the exchange plan (ISSUE 16); None reads
        ``SRJT_CLUSTER_TOPOLOGY``:

        - ``all_to_all`` — every rank publishes world-1 partitions and
          pulls its own from every peer concurrently (the direct plan;
          any world size);
        - ``tree`` — the hypercube plan for power-of-two worlds:
          log2(world) rounds, one partner per round, each rank moving
          ONE coalesced frame per round instead of world-1 frames
          total — fewer, larger transfers when world grows;
        - ``auto`` — tree for power-of-two worlds >= 4, else
          all_to_all.

        ``cluster`` (a ``parallel.cluster.ClusterView``) arms failover:
        a pull that exhausts its retries against a peer the cluster has
        declared DEAD is recomputed from that rank's input lineage and
        re-published under the bumped generation instead of erroring
        the query. Recovery needs single-hop lineage — every partition
        moves source -> destination directly — so an attached cluster
        pins ``all_to_all``: a tree round forwards OTHER ranks' rows,
        whose loss would need a whole-world replay to reconstruct."""
        from ..utils import knobs

        world = len(peers) + 1
        ranks = sorted(set(peers) | {self.rank})
        if len(ranks) != world or ranks != list(range(world)):
            raise ValueError(
                f"exchange peers must cover ranks 0..{world - 1} "
                f"(got self={self.rank}, peers={sorted(peers)})"
            )
        if topology is None:
            topology = knobs.get_str("SRJT_CLUSTER_TOPOLOGY")
        if topology == "auto":
            topology = (
                "tree"
                if cluster is None and world >= 4 and world & (world - 1) == 0
                else "all_to_all"
            )
        if topology == "tree" and cluster is not None:
            topology = "all_to_all"  # recovery needs single-hop lineage
        if topology == "tree":
            if world < 2 or world & (world - 1):
                raise ValueError(
                    f"tree exchange needs a power-of-two world, got {world}"
                )
            return self._exchange_tree(table, key_cols, peers, epoch)
        if topology != "all_to_all":
            raise ValueError(f"unknown exchange topology {topology!r}")
        return self._exchange_all_to_all(table, key_cols, peers, epoch, cluster)

    def _exchange_all_to_all(self, table: "Table", key_cols: Sequence[str],
                             peers: Dict[int, str], epoch: int,
                             cluster=None) -> "Table":
        """The direct plan: publish world-1 outgoing partitions, pull
        this rank's partition from every peer, concatenate in rank
        order. With ``cluster`` armed, a pull whose peer the cluster
        declares dead fails over to the lineage-recomputed copy."""
        from ..ops.copying import concatenate, slice_table

        world = len(peers) + 1
        ranks = sorted(set(peers) | {self.rank})
        partitioned, offsets = hash_partition(table, world, key_cols)
        bounds = list(offsets) + [partitioned.num_rows]
        parts = {
            p: slice_table(partitioned, bounds[p], bounds[p + 1])
            for p in range(world)
        }
        self.publish(epoch, {p: t for p, t in parts.items() if p != self.rank})
        # pull every peer's partition CONCURRENTLY (wall-clock = the
        # slowest peer, not the sum; a slow peer must not stall pulls
        # from peers already serving), then reassemble in rank order so
        # row order — and therefore downstream aggregation — stays
        # deterministic. contextvars.copy_context() carries the
        # caller's deadline scope into each fetch thread (retry arming
        # is module-global and inherits on its own).
        import contextvars

        fetched: Dict[int, "Table"] = {}
        errs: List[BaseException] = []

        def _pull(r: int, addr: str, ctx) -> None:
            try:
                fetched[r] = ctx.run(self.fetch, addr, epoch, self.rank)
                return
            except BaseException as e:  # srjt-lint: allow-broad-except(thread-exit funnel: the joiner re-raises errs[0] after joining every fetch thread)
                if cluster is None:
                    errs.append(e)
                    return
                primary = e
            # failover (ISSUE 16): only after the retry budget is spent
            # AND the membership layer agrees the peer is dead does the
            # pull switch to the recomputed copy — a slow peer keeps
            # its retryable error, a dead one stops erroring the query
            try:
                recovered = ctx.run(
                    cluster.failover_fetch, r, epoch, list(key_cols),
                    world, self.rank,
                )
            except BaseException as e2:  # srjt-lint: allow-broad-except(thread-exit funnel: the joiner re-raises errs[0] after joining every fetch thread)
                errs.append(e2)
                return
            if recovered is None:
                errs.append(primary)
            else:
                fetched[r] = recovered

        pulls = [
            threading.Thread(
                target=_pull, args=(r, peers[r], contextvars.copy_context())
            )
            for r in ranks
            if r != self.rank
        ]
        for t in pulls:
            t.start()
        for t in pulls:
            t.join()
        if errs:
            raise errs[0]
        received = []
        names = list(table.names)
        for r in ranks:
            if r == self.rank:
                received.append(parts[self.rank])
            else:
                # frames carry schema (dtypes/validity), not names —
                # the caller owns the naming, so re-apply its schema
                received.append(Table(fetched[r].columns, names))
        return concatenate(received)

    def _exchange_tree(self, table: "Table", key_cols: Sequence[str],
                       peers: Dict[int, str], epoch: int) -> "Table":
        """The hypercube plan (power-of-two worlds): log2(world)
        dimension-ordered rounds; in round j this rank exchanges ONE
        coalesced frame with ``partner = rank ^ (1 << j)``, handing
        over every held row whose destination differs from ours in bit
        j. After round j all held rows agree with this rank on bits
        0..j, so after the last round every row is home. Intermediate
        frames are keyed at ``epoch + (j+1) * _TREE_EPOCH_STRIDE`` —
        a derived namespace a real round never occupies. Round skew
        between partners is bounded at one (a rank cannot start round
        j+1 before its partner finishes round j), so the retain-epochs
        eviction window is never outrun.

        Determinism: each round rebuilds the held table as the rank-
        ordered kept partitions followed by the partner's frame, so
        the final row order is a pure function of (table, key_cols,
        world, rank) — the same bit-for-bit reproducibility contract
        as the direct plan, though the two plans' row ORDERS differ
        (order-sensitive consumers must aggregate order-independently,
        which the exact f64 accumulator and integer sums both are)."""
        from ..ops.copying import concatenate, slice_table

        world = len(peers) + 1
        names = list(table.names)
        held = table
        rounds = world.bit_length() - 1
        for j in range(rounds):
            partner = self.rank ^ (1 << j)
            sub_epoch = int(epoch) + (j + 1) * _TREE_EPOCH_STRIDE
            partitioned, offsets = hash_partition(held, world, key_cols)
            bounds = list(offsets) + [partitioned.num_rows]
            keep: List["Table"] = []
            send: List["Table"] = []
            mine_j = (self.rank >> j) & 1
            for p in range(world):
                seg = slice_table(partitioned, bounds[p], bounds[p + 1])
                ((keep if ((p >> j) & 1) == mine_j else send).append(seg))
            self.publish(sub_epoch, {partner: concatenate(send)})
            got = self.fetch(peers[partner], sub_epoch, self.rank)
            held = concatenate(keep + [Table(got.columns, names)])
        return held

    def close(self) -> None:
        with self._published:
            self._closed = True
            self._frames.clear()
            self._published.notify_all()
        try:
            self._srv.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# two-process harness: the CLI tests/test_data_plane.py and
# benchmarks/bench_pool.py spawn as the peer rank. Deterministic by
# construction (seeded data, integer-exact sums), so a respawned
# incarnation recomputes and republishes identical partitions — which
# is what makes a kill -9'd peer survivable by plain refetching.
# ---------------------------------------------------------------------------


def _demo_table(rows: int, seed: int, num_keys: int = 64) -> Table:
    """The harness's deterministic workload: int64 keys + int64 values
    (integer sums are associative bit-for-bit, so the distributed
    result is comparable to the single-process one exactly)."""
    import numpy as np  # noqa: F811  (module-level np is fine; explicit)

    from ..columnar import Column, Table as _Table
    from ..columnar.dtype import INT64

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, num_keys, rows).astype(np.int64)
    vals = rng.integers(-1000, 1000, rows).astype(np.int64)
    return _Table(
        [Column(INT64, data=jnp.asarray(keys)), Column(INT64, data=jnp.asarray(vals))],
        ["k", "v"],
    )


def _local_groupby_sum(table: Table) -> Table:
    """Exact int64 groupby (sum + count) over the harness table,
    sorted by key — the deterministic per-rank aggregation whose
    concatenation must be bit-identical to the single-process run."""
    import numpy as np

    from ..columnar import Column, Table as _Table
    from ..columnar.dtype import INT64

    keys = np.asarray(table.column("k").data)
    vals = np.asarray(table.column("v").data)
    uniq, inv = np.unique(keys, return_inverse=True)
    sums = np.zeros(len(uniq), np.int64)
    counts = np.zeros(len(uniq), np.int64)
    np.add.at(sums, inv, vals)
    np.add.at(counts, inv, 1)
    return _Table(
        [
            Column(INT64, data=jnp.asarray(uniq)),
            Column(INT64, data=jnp.asarray(sums)),
            Column(INT64, data=jnp.asarray(counts)),
        ],
        ["k", "s", "c"],
    )


def _shard_bounds(rows: int, world: int, rank: int) -> Tuple[int, int]:
    return rows * rank // world, rows * (rank + 1) // world


def format_peers(peers: Dict[int, str]) -> str:
    """``rank=host:port,...`` — the ``--peers`` CLI / stdin-update
    encoding (one owner, both directions parse through
    ``parse_peers``)."""
    return ",".join(f"{r}={a}" for r, a in sorted(peers.items()))


def parse_peers(spec: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    for item in (spec or "").split(","):
        if not item:
            continue
        r, _, addr = item.partition("=")
        out[int(r)] = addr
    return out


def send_peer_map(proc, peers: Dict[int, str]) -> None:
    """Second half of the N-rank spawn handshake (ISSUE 16): ranks
    spawn knowing only rank 0's address (later ranks' ports do not
    exist yet), so once every READY line is in, the spawner completes
    each child's world view with one ``EXCHANGE_PEER_MAP`` line on
    its stdin. A world-2 child already knows its whole world and skips
    the wait, so the two-process tests keep their close-stdin flow."""
    proc.stdin.write(f"EXCHANGE_PEER_MAP {format_peers(peers)}\n")
    proc.stdin.flush()


def spawn_exchange_peer(parent_addr: str, rows: int, seed: int, *,
                        rank: int = 1, world: int = 2,
                        extra_env: Optional[dict] = None,
                        ready_timeout_s: float = 180.0,
                        respawn_of=None,
                        cluster: bool = False,
                        query: str = "demo",
                        epoch: int = 0,
                        rounds: int = 1):
    """Spawn one ``--exchange-worker`` peer process against
    ``parent_addr`` (rank 0) and wait for its READY handshake; returns
    ``(Popen, peer_address)``. The ONE owner of the spawn/handshake
    protocol — tests and benchmarks both go through it, so a change to
    the CLI flags or the READY line cannot drift between them. The
    child inherits this environment minus any armed fault-injection
    config (pass it back via ``extra_env`` to storm the peer on
    purpose), with retry armed and ``SRJT_FAULTINJ_RANK=r<rank>``
    stamped so ``@r<N>``-keyed chaos rules resolve in the right
    process. For ``world > 2`` the child knows only rank 0 at spawn;
    complete its peer map with ``send_peer_map`` once every rank's
    address is known. ``cluster=True`` arms the worker's ClusterView
    (membership + heartbeats + lineage recovery); ``query`` picks the
    workload (``demo`` groupby or the ``q55`` plan-compiler run).
    ``respawn_of`` is the Popen of a DEAD predecessor being replaced:
    the harness verifies it exited and emits the
    ``exchange.peer_respawn`` event itself — the artifact the premerge
    chaos gate asserts on, so it must come from the machinery that
    observed the death, never from a test's own assertion."""
    import subprocess
    import sys

    from ..utils.errors import FatalDeviceError

    env = dict(os.environ)
    env.pop("SRJT_FAULTINJ_CONFIG", None)
    env["SRJT_RETRY_ENABLED"] = "1"
    env["SRJT_FAULTINJ_RANK"] = f"r{rank}"
    if extra_env:
        env.update(extra_env)
    runner = (
        "from spark_rapids_jni_tpu.parallel.shuffle import _main; "
        "import sys; sys.exit(_main())"
    )
    argv = [sys.executable, "-c", runner,
            "--exchange-worker", "--rank", str(rank), "--world", str(world),
            "--rows", str(rows), "--seed", str(seed),
            "--epoch", str(epoch), "--query", query,
            "--rounds", str(rounds),
            "--peers", f"0={parent_addr}"]
    if cluster:
        argv.append("--cluster")
    proc = subprocess.Popen(
        argv,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, text=True,
    )
    import select

    # select() on the RAW fd + os.read into our own line buffer so
    # ready_timeout_s is actually enforced: a child that wedges before
    # printing (jax init hang) must not park the parent in a
    # timeout-less readline, an EOF while the child lives means READY
    # can never arrive (fail fast, never busy-spin on empty reads),
    # and a READY line that lands in the same pipe chunk as an earlier
    # stdout line must still be seen — selecting on the buffered text
    # stream would never report it readable again (the data already
    # left the pipe) and a healthy peer would be killed at timeout
    fd = proc.stdout.fileno()
    buf = b""
    t_end = time.monotonic() + ready_timeout_s
    while True:
        nl = buf.find(b"\n")
        if nl >= 0:
            line, buf = buf[:nl], buf[nl + 1:]
            text = line.decode("utf-8", "replace")
            if text.startswith("SRJT_EXCHANGE_READY"):
                if respawn_of is not None and respawn_of.poll() is not None:
                    from ..utils import metrics

                    metrics.event(
                        "exchange.peer_respawn", rank=rank,
                        prev_rc=respawn_of.returncode,
                    )
                return proc, text.strip().split("addr=")[1]
            continue
        remaining = t_end - time.monotonic()
        if remaining <= 0:
            break
        readable, _, _ = select.select([fd], [], [], min(remaining, 0.5))
        if not readable:
            if proc.poll() is not None:
                raise FatalDeviceError(
                    f"exchange peer exited during startup rc={proc.returncode}"
                )
            continue
        chunk = os.read(fd, 65536)
        if not chunk:
            if proc.poll() is not None:
                raise FatalDeviceError(
                    f"exchange peer exited during startup rc={proc.returncode}"
                )
            proc.kill()
            proc.wait()
            raise FatalDeviceError(
                "exchange peer closed stdout before reporting ready"
            )
        buf += chunk
    proc.kill()
    proc.wait()
    raise FatalDeviceError(
        f"exchange peer never reported ready within {ready_timeout_s:g}s"
    )


def spawn_exchange_fleet(parent_addr: str, rows: int, seed: int, *,
                         world: int,
                         cluster: bool = False,
                         query: str = "demo",
                         epoch: int = 0,
                         rounds: int = 1,
                         extra_env_by_rank: Optional[dict] = None,
                         ready_timeout_s: float = 180.0):
    """Spawn ranks ``1..world-1`` as ``--exchange-worker`` processes
    (this process is rank 0 at ``parent_addr``), complete every
    child's peer map once all READY lines are in, and return
    ``(procs, peers)`` — ``procs[rank] -> Popen``, ``peers[rank] ->
    address`` for every rank including 0. The one owner of the
    multi-rank bring-up sequence so the chaos tier, the scaling bench,
    and the tests cannot drift on the handshake. On any spawn failure
    the already-started children are killed before the error
    propagates (no orphan servers squatting on ports)."""
    procs: Dict[int, object] = {}
    peers: Dict[int, str] = {0: parent_addr}
    try:
        for rank in range(1, world):
            proc, addr = spawn_exchange_peer(
                parent_addr, rows, seed, rank=rank, world=world,
                cluster=cluster, query=query, epoch=epoch, rounds=rounds,
                ready_timeout_s=ready_timeout_s,
                extra_env=(extra_env_by_rank or {}).get(rank),
            )
            procs[rank] = proc
            peers[rank] = addr
        if world > 2:
            for rank, proc in procs.items():
                send_peer_map(proc, {r: a for r, a in peers.items()
                                     if r != rank})
    except BaseException:
        for proc in procs.values():
            proc.kill()
            proc.wait()
        raise
    return procs, peers


def _await_peer_map(peers: Dict[int, str], world: int) -> bool:
    """Block on stdin until the spawner's ``EXCHANGE_PEER_MAP`` line
    completes the rank→address map (``send_peer_map`` is the sender).
    Returns False on EOF before the map arrived — the spawner died, so
    the worker must exit rather than exchange against a partial
    world."""
    import sys

    while len(peers) < world - 1:
        line = sys.stdin.readline()
        if not line:
            return False
        if line.startswith("EXCHANGE_PEER_MAP "):
            peers.update(parse_peers(line.split(" ", 1)[1].strip()))
    return True


def _worker_run_q55(ex: "TcpExchange", peers: Dict[int, str], cluster,
                    args) -> Table:
    """The distributed TPC-DS leg of the worker: compile q55 with
    exchange stages, run it over this rank's store_sales shard, and
    return the per-rank partial (concatenating every rank's partial
    and re-sorting reproduces the single-host answer bit-for-bit —
    `ops/f64acc` sums are order-independent and the sort keys are a
    total order)."""
    from ..models import tpcds
    from ..models.tpcds_plans import q55_plan
    from ..ops.copying import slice_table
    from ..plan import compile_ir
    from ..plan.distribute import exchange_context, insert_exchanges

    tables = tpcds.gen_store(args.rows, seed=args.seed)
    world = args.world
    sales = tables["store_sales"]

    def shard_tables(r: int) -> Dict[str, Table]:
        lo, hi = _shard_bounds(sales.num_rows, world, r)
        shards = dict(tables)
        shards["store_sales"] = slice_table(sales, lo, hi)
        return shards

    plan = insert_exchanges(q55_plan(), world)
    compiled = compile_ir(plan, shard_tables(args.rank),
                          name=f"q55@r{args.rank}")
    with exchange_context(ex, peers, cluster=cluster,
                          shard_tables=shard_tables, base_epoch=args.epoch):
        return compiled()


def _exchange_worker_main(args) -> int:
    """Peer-rank process: build the deterministic shard, exchange hash
    partitions with the rest of the world, aggregate, publish the
    result table (epoch ``args.epoch + 1``, part = this rank), then
    park until stdin closes. Prints ``SRJT_EXCHANGE_READY
    addr=<host:port>`` once the server is up — the line the parent
    polls for; for ``world > 2`` it then blocks until the spawner's
    ``EXCHANGE_PEER_MAP`` stdin line completes the rank→address map
    (only rank 0's address exists at spawn time). ``--cluster`` arms a
    ClusterView (generation fencing + heartbeats + lineage recovery);
    ``--query q55`` swaps the demo groupby for the plan-compiled
    distributed TPC-DS q55. The worker IS the cross-process posture,
    so it defaults ``SRJT_EXCHANGE_MODE`` to ``tcp`` and refuses an
    explicit ``mesh`` (an operator forcing the in-process mode on a
    cross-process peer is a config error, not something to ignore)."""
    import sys

    from ..ops.copying import slice_table
    from ..utils import retry

    os.environ.setdefault("SRJT_EXCHANGE_MODE", "tcp")
    if exchange_mode() != "tcp":
        print(
            "exchange worker: SRJT_EXCHANGE_MODE must be 'tcp' for a "
            "cross-process peer (got 'mesh')",
            file=sys.stderr,
        )
        return 2

    peers = parse_peers(args.peers)
    table = shard = None
    if args.query == "demo":
        # warm before READY: the demo shard and its partition/groupby
        # compiles depend only on argv, and the spawner's measurement
        # window opens at the handshake — compile time is not exchange
        # throughput, so pay for it here
        from ..columnar import frames as frames_mod

        table = _demo_table(args.rows, args.seed)
        lo, hi = _shard_bounds(args.rows, args.world, args.rank)
        shard = slice_table(table, lo, hi)
        parts_w, offs_w = hash_partition(shard, args.world, ["k"])
        bounds_w = list(offs_w) + [parts_w.num_rows]
        for p in range(args.world):
            if p != args.rank:  # the exact frames publish() will encode
                frames_mod.encode_table(
                    slice_table(parts_w, bounds_w[p], bounds_w[p + 1]))
        _local_groupby_sum(slice_table(shard, 0, min(shard.num_rows, 1024)))
    ex = TcpExchange(args.rank, bind=args.bind)
    print(f"SRJT_EXCHANGE_READY addr={ex.address}", flush=True)
    if not _await_peer_map(peers, args.world):
        print("exchange worker: stdin closed before peer map arrived",
              file=sys.stderr)
        ex.close()
        return 3

    cluster = None
    if args.cluster:
        from .cluster import ClusterView

        addresses = dict(peers)
        addresses[args.rank] = ex.address
        cluster = ClusterView(args.rank, addresses, ex)
        cluster.start()

    try:
        with retry.enabled(max_attempts=40, base_delay_ms=25,
                           max_delay_ms=250):
            if args.query == "q55":
                result = _worker_run_q55(ex, peers, cluster, args)
                result_epoch = args.epoch + 1
            else:
                if cluster is not None:
                    cluster.set_lineage(lambda r: slice_table(
                        table, *_shard_bounds(args.rows, args.world, r)))
                # `--rounds N` repeats the exchange at even epoch
                # offsets (round i at epoch + 2i) so the scaling bench
                # can time a steady-state round with every per-shape
                # compile already paid; rounds=1 keeps the historical
                # epoch/epoch+1 scheme. Max inter-rank skew is one
                # round (a rank cannot finish round i before every
                # rank published it), which retain_epochs=4 outlives.
                for rnd in range(max(args.rounds, 1)):
                    local = ex.exchange_table(
                        shard, ["k"], peers,
                        epoch=args.epoch + 2 * rnd, cluster=cluster)
                result = _local_groupby_sum(local)
                result_epoch = args.epoch + 2 * max(args.rounds, 1) - 1
            ex.publish(result_epoch, {args.rank: result})
            # park: serve fetches until the supervisor closes our stdin
            sys.stdin.read()
    finally:
        if cluster is not None:
            cluster.stop()
        ex.close()
    return 0


def _main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description="TCP exchange worker harness")
    ap.add_argument("--exchange-worker", action="store_true", required=True)
    ap.add_argument("--rank", type=int, default=1)
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--bind", default="127.0.0.1:0")
    ap.add_argument("--peers", default="", help="rank=host:port,...")
    ap.add_argument("--cluster", action="store_true",
                    help="arm ClusterView membership + heartbeats")
    ap.add_argument("--query", default="demo", choices=("demo", "q55"),
                    help="workload: demo groupby or plan-compiled q55")
    ap.add_argument("--rounds", type=int, default=1,
                    help="demo exchange rounds (round i at epoch + 2i; "
                         "result published at epoch + 2*rounds - 1)")
    return _exchange_worker_main(ap.parse_args())


if __name__ == "__main__":
    import sys

    sys.exit(_main())

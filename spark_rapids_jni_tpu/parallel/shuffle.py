"""Shuffle: hash partition + the ICI all-to-all exchange.

Replaces the UCX/NVLink RapidsShuffleManager path (SURVEY §2.9, §5
"distributed communication backend"): executor-partitioned row batches
are repartitioned with ONE ``lax.all_to_all`` over the mesh's data axis
inside ``shard_map`` — on-pod exchanges ride ICI; put a ``dcn`` outer
axis on the mesh and XLA layers the collective across pods.

Static-shape framing (XLA compiles one program, no data-dependent
shapes): each shard scatters its rows into a [P, capacity] bucket
matrix + occupancy mask, all_to_all swaps bucket axes, receivers get
[P, capacity] from every peer. ``capacity`` bounds rows any shard may
send to one destination; overflow RAISES RetryableError by default
(no silent-drop path — VERDICT r3 item 8), with ``on_overflow="flag"``
as the opt-in contract for capacity-managing callers that recompute
and retry, and ``on_overflow="retry"`` as the self-healing contract:
the exchange doubles capacity (geometric, bounded) and re-executes
in-op (utils/retry.py orchestrator counters record each escalation).
Compaction back to dense rows happens host-side or in the consuming
kernel via the mask.

Observability (utils/metrics.py, SRJT_METRICS_ENABLED=1): every
exchange execution records its WIRE footprint — the capacity-padded
[n_parts, capacity] bucket bytes the collective actually moves, per
attempt, not the dense row payload — into
``shuffle.bytes_exchanged``; a completed exchange adds a wall-clock
histogram entry (``shuffle.exchange_us``) and an event-log line, and
each capacity escalation bumps ``shuffle.capacity_retries`` and logs
the old->new capacity — the Thallus-style transport-layer
instrumentation the VERDICT scan->agg GB/s artifacts read.

Integrity (ISSUE 5, utils/integrity.py): with checks armed (the
default) every completed exchange verifies an order-independent
payload checksum — the wraparound-u64 sum of every lane's bit pattern,
invariant under the row permutation the collective performs — plus the
occupied-slot count against the rows sent. A mismatch raises retryable
``DataCorruption`` (op_boundary's armed retry re-executes the
exchange), counted under ``sidecar.integrity.crc_mismatch`` — the
Thallus posture: transport corruption must be an error, never rows.

Cross-process TCP exchange (ISSUE 6): the in-mesh collective above
remains the fast path WITHIN one runtime; ``TcpExchange`` adds the
cross-PROCESS mode — two single-host runtimes exchanging hash
partitions as versioned columnar frames (columnar/frames.py, the same
codec sidecar wire payloads and memgov spills use) over plain TCP
sockets. Pull-based: each peer serves its published partitions, so the
deadline/retry/breaker/CRC machinery rides the FETCH side unchanged —
a tampered frame decodes to retryable ``DataCorruption`` and the retry
re-fetches; a crashed peer is a connection fault the retry outlives
(supervisors respawn peers; published partitions are recomputed
deterministically). ``SRJT_EXCHANGE_MODE`` (default ``mesh``) is the
transport selector for callers that host a cross-process rank — the
exchange-worker harness and benchmarks consult ``exchange_mode()``;
the in-library collectives (``exchange_by_key`` etc.) always use the
mesh and ignore it. Peers are addressed ``rank=host:port``. The
two-process
harness behind ``python -m spark_rapids_jni_tpu.parallel.shuffle
--exchange-worker`` drives the distributed-groupby acceptance test and
``benchmarks/bench_pool.py``'s exchange MB/s row.
"""

from __future__ import annotations

import os
import socket as socket_mod
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..columnar import Table
from ..columnar.dtype import TypeId
from ..ops.hashing import hash_partition_map
from ..ops.copying import gather
from ..utils.dispatch import op_boundary
from ._smcache import cached_sm, shard_map

__all__ = [
    "hash_partition",
    "all_to_all_exchange",
    "exchange_by_key",
    "exchange_mode",
    "TcpExchange",
    "exchange_breaker",
    "spawn_exchange_peer",
]


@op_boundary("hash_partition")
def hash_partition(table: Table, num_partitions: int, key_cols: Sequence[str]) -> Tuple[Table, List[int]]:
    """Single-device cudf-style hash_partition: rows reordered so each
    partition is contiguous; returns (table, partition start offsets)."""
    pmap = hash_partition_map([table.column(c) for c in key_cols], num_partitions)
    order = jnp.argsort(pmap, stable=True).astype(jnp.int32)
    out = gather(table, order)
    counts = np.bincount(np.asarray(pmap), minlength=num_partitions)
    offsets = np.concatenate([[0], np.cumsum(counts)])[:-1].tolist()
    return out, offsets


def _exchange_checksum(arrays) -> int:
    """Order-independent payload checksum for the all-to-all (ISSUE 5,
    utils/integrity.py): the exchange PERMUTES rows across shards, so a
    positional CRC cannot survive it — the invariant is the byte
    MULTISET, summarized as the wraparound-u64 sum of every lane's bit
    pattern. Unoccupied bucket slots are zero-initialized and add
    nothing, so the sum over the capacity-padded receive buffers equals
    the sum over the dense send payload exactly when every row landed
    intact. Computed on device (one reduction per array), no host copy."""
    from jax import lax as _lax

    total = 0
    for a in arrays:
        if a.dtype == jnp.bool_:
            v = a.astype(jnp.uint8)
        else:
            v = _lax.bitcast_convert_type(
                a, jnp.dtype(f"uint{a.dtype.itemsize * 8}")
            )
        total = (total + int(jnp.sum(v.astype(jnp.uint64)))) & 0xFFFFFFFFFFFFFFFF
    return total


def _bucketize(vals: jnp.ndarray, dest: jnp.ndarray, n_parts: int, capacity: int):
    """Per-shard scatter of [n] rows into [P, capacity] buckets.

    Returns (buckets, mask, overflow). Rows beyond capacity for their
    destination are dropped and flagged.
    """
    n = dest.shape[0]
    order = jnp.argsort(dest)  # group rows by destination
    d_sorted = dest[order]
    # position within destination bucket: index along the sorted run
    idx = jnp.arange(n, dtype=jnp.int32)
    run_start = jnp.searchsorted(d_sorted, jnp.arange(n_parts, dtype=jnp.int32), side="left")
    slot = idx - run_start[d_sorted]
    overflow = jnp.any(slot >= capacity)
    keep = slot < capacity
    # overflowing rows scatter out of range and are dropped (mode="drop"),
    # never aliasing the legitimate occupant of the last slot
    flat = jnp.where(keep, d_sorted.astype(jnp.int32) * capacity + slot, n_parts * capacity)

    shape = (n_parts * capacity,) + vals.shape[1:]
    buckets = jnp.zeros(shape, vals.dtype)
    buckets = buckets.at[flat].set(vals[order], mode="drop")
    mask = jnp.zeros((n_parts * capacity,), bool).at[flat].set(True, mode="drop")
    return (
        buckets.reshape((n_parts, capacity) + vals.shape[1:]),
        mask.reshape(n_parts, capacity),
        overflow,
    )


def _exchange_once(arrays, dest, mesh: Mesh, axis: str, capacity: int, n_parts: int):
    """One all-to-all execution at a fixed capacity."""

    def body(dest_local, *arrs):
        outs = []
        ovf = jnp.zeros((), bool)
        mask = None
        for a in arrs:
            b, m, o = _bucketize(a, dest_local, n_parts, capacity)
            # all_to_all: split axis 0 (destinations), concat received
            r = lax.all_to_all(b, axis, split_axis=0, concat_axis=0, tiled=True)
            outs.append(r)
            ovf = ovf | o
            mask = m
        rm = lax.all_to_all(mask, axis, split_axis=0, concat_axis=0, tiled=True)
        return tuple(outs) + (rm, ovf[None])

    spec = P(axis)
    in_specs = (spec,) + tuple(spec for _ in arrays)
    out_specs = tuple(spec for _ in arrays) + (spec, spec)
    f = cached_sm(
        ("a2a_exchange", mesh, axis, int(capacity), len(arrays),
         tuple(str(a.dtype) for a in arrays)),
        lambda: jax.jit(shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs)),
    )
    *received, recv_mask, overflow = f(dest, *arrays)
    return received, recv_mask, overflow


@op_boundary("all_to_all_exchange")
def all_to_all_exchange(
    arrays: Sequence[jnp.ndarray],
    dest: jnp.ndarray,
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    on_overflow: str = "raise",
):
    """Exchange row-sharded arrays so row i lands on shard dest[i].

    arrays: row-sharded along `axis` ([N_global, ...] each); dest:
    [N_global] int32 in [0, mesh axis size). Returns (received_arrays,
    recv_mask, overflow): received arrays are [P * capacity * ...] per
    shard, i.e. globally [N_shards, P, capacity, ...] flattened on the
    leading axis, with recv_mask marking occupied slots.

    Overflow semantics (VERDICT r3 item 8): a caller-supplied capacity
    that a skewed destination exceeds can NOT silently hand back
    truncated data. ``on_overflow="raise"`` (default) raises
    ``RetryableError`` — the Spark task-retry class; capacity-managing
    callers (the Table tier recomputes and retries) opt into the
    flag-only contract with ``on_overflow="flag"``; and
    ``on_overflow="retry"`` closes the loop IN-OP: the exchange doubles
    the capacity (geometric, bounded by the per-shard ceiling that
    cannot overflow) and re-executes until every row lands — the UCX
    shuffle transient-failure posture, wired through the retry
    orchestrator's counters (utils/retry.py). The defaulted capacity
    (= rows per shard) cannot overflow.
    """
    if on_overflow not in ("raise", "flag", "retry"):
        raise ValueError(
            f"on_overflow must be 'raise', 'flag', or 'retry', got {on_overflow!r}"
        )
    if capacity is not None and capacity < 1:
        # capacity=0 would make the geometric escalation a fixed point
        # (2*0 == 0): the retry loop must always be able to grow
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    n_parts = mesh.shape[axis]
    n_global = dest.shape[0]
    per_shard = n_global // n_parts
    if capacity is None:
        capacity = per_shard  # safe: one shard can absorb everything

    from .. import memgov
    from ..utils import integrity, metrics

    armed = metrics.is_enabled()
    governed = on_overflow == "retry" and memgov.is_enabled()
    # integrity (ISSUE 5): checksum the payload entering the collective
    # so a corrupted/dropped row surfaces as retryable DataCorruption
    # (op_boundary's armed retry re-executes), never as wrong rows
    checked = integrity.is_enabled()
    sent_sum = _exchange_checksum(arrays) if checked else None
    # per-GLOBAL-ROW wire cost: the collective moves capacity-padded
    # [n_parts, capacity] buckets per shard per array (NOT the dense
    # row payload) plus the 1-byte/slot occupancy mask — the padded
    # footprint is what a GB/s artifact must divide by, and it changes
    # each time the escalation loop doubles capacity. ONE cost model:
    # the metrics wire accounting and the governor's escalation
    # estimate read the same number
    row_bytes = (
        sum(int(a.nbytes) // max(a.shape[0], 1) for a in arrays) + 1
        if armed or governed else 0
    )
    t0 = time.perf_counter() if armed else 0.0
    wire_bytes = 0
    while True:
        received, recv_mask, overflow = _exchange_once(
            arrays, dest, mesh, axis, int(capacity), n_parts
        )
        if armed:
            # bytes THIS execution put on the wire (failed-overflow
            # attempts moved their buckets too, so accumulate per try)
            attempt_bytes = n_parts * n_parts * int(capacity) * row_bytes
            wire_bytes += attempt_bytes
            metrics.counter("shuffle.bytes_exchanged").inc(attempt_bytes)
        overflowed = bool(np.asarray(overflow).any())
        if not overflowed or on_overflow == "flag":
            if checked and not overflowed:
                # verify only complete exchanges: a flagged overflow
                # legitimately dropped rows, which is the CALLER's
                # recompute contract, not corruption
                from ..utils import metrics as _m

                _m.registry().counter("sidecar.integrity.exchanges_checked").inc()
                recv_sum = _exchange_checksum(received)
                recv_rows = int(jnp.sum(recv_mask.astype(jnp.uint64)))
                if recv_sum != sent_sum or recv_rows != int(n_global):
                    raise integrity.raise_corruption(
                        "shuffle.exchange",
                        f"sent 0x{sent_sum:016x}/{int(n_global)} rows != "
                        f"recv 0x{recv_sum:016x}/{recv_rows} rows",
                    )
            if armed:
                elapsed = time.perf_counter() - t0
                metrics.counter("shuffle.exchanges").inc()
                metrics.histogram("shuffle.exchange_us").record(elapsed * 1e6)
                metrics.event(
                    "shuffle.exchange", axis=axis, n_parts=n_parts,
                    capacity=int(capacity), wire_bytes=wire_bytes,
                    wall_us=round(elapsed * 1e6, 1),
                    overflow=overflowed,
                )
            return received, recv_mask, overflow
        if on_overflow == "retry" and capacity < per_shard:
            # the capacity re-try loop consults the deadline/cancel
            # token BETWEEN attempts (utils/deadline.py): an escalated
            # re-execution never starts once the query budget is gone
            from ..utils import deadline as deadline_mod

            deadline_mod.check("all_to_all_exchange.capacity_retry")
            # geometric escalation: at most ceil(log2(per_shard/cap0))
            # re-executions before the cannot-overflow ceiling
            new_capacity = min(2 * int(capacity), per_shard)
            # memory governor (memgov/, ISSUE 4): the doubled bucket
            # matrices are a footprint the op's original admission never
            # covered — route the escalated estimate through the
            # controller (which GROWS the held admission on success) so
            # a doubling that cannot fit spills cold catalog buffers or
            # raises the retryable MemoryBudgetExceeded (the split
            # path), never an XLA OOM
            if governed:
                from ..utils.memory import exchange_bytes_estimate

                memgov.ensure_fits(
                    exchange_bytes_estimate(
                        row_bytes, n_parts, int(new_capacity)
                    ),
                    "all_to_all_exchange.capacity_retry",
                )
            metrics.event(
                "shuffle.capacity_escalation", axis=axis,
                capacity=int(capacity), new_capacity=int(new_capacity),
            )
            capacity = new_capacity
            from ..utils import retry as retry_mod

            retry_mod.record_capacity_retry()
            continue
        from ..utils.errors import RetryableError

        raise RetryableError(
            f"all_to_all_exchange: a destination shard received more than "
            f"capacity={capacity} rows; retry with a larger capacity "
            f"(rows would otherwise be dropped)"
        )


@op_boundary("exchange_by_key")
def exchange_by_key(
    table: Table,
    key_cols: Sequence[str],
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    on_overflow: str = "raise",
):
    """Hash-repartition a row-sharded fixed-width Table over the mesh.

    Returns (pairs_by_column, recv_mask, overflow) where each pair is
    (data, validity-or-None) — null masks travel with their column so
    null rows stay null on the receiving shard. Rows of one key all land
    on the same shard (hash pmod, ops/hashing parity with the
    single-device partitioner).

    ``on_overflow="retry"`` makes a capacity overflow self-healing: the
    exchange doubles ``capacity`` (geometric, bounded by the per-shard
    ceiling) and re-executes the all-to-all instead of raising — the
    shuffle-side half of the retry orchestrator (utils/retry.py).
    """
    if on_overflow not in ("raise", "flag", "retry"):
        raise ValueError(
            f"on_overflow must be 'raise', 'flag', or 'retry', got {on_overflow!r}"
        )
    for c in table.columns:
        if c.dtype.id in (TypeId.STRING, TypeId.LIST):
            raise ValueError(
                "exchange_by_key moves fixed-width payloads; use "
                "parallel.table_ops.exchange_table, which dictionary-encodes "
                "string columns automatically"
            )
    dest = hash_partition_map([table.column(c) for c in key_cols], mesh.shape[axis])
    arrays: List[jnp.ndarray] = []
    has_validity: List[bool] = []
    for c in table.columns:
        arrays.append(c.data)
        has_validity.append(c.validity is not None)
        if c.validity is not None:
            arrays.append(c.validity)
    received, recv_mask, overflow = all_to_all_exchange(
        arrays, dest.astype(jnp.int32), mesh, axis, capacity, on_overflow=on_overflow
    )
    pairs = []
    it = iter(received)
    for nullable in has_validity:
        data = next(it)
        pairs.append((data, next(it) if nullable else None))
    return pairs, recv_mask, overflow


# ---------------------------------------------------------------------------
# cross-process TCP exchange (ISSUE 6): hash partitions as columnar
# frames between two single-host runtimes, pull-based so deadline +
# retry + breaker + CRC ride the fetch side unchanged
# ---------------------------------------------------------------------------

_EXC_MAGIC = b"SRJTEXC1"
_EXC_REQ = struct.Struct("<8sIII")  # magic, verb, epoch, part
_EXC_RESP = struct.Struct("<IQ")  # status, payload length
_EXC_GET = 1
# srjt-trace (ISSUE 12): GET whose request carries a 17-byte trace
# context (utils/tracing.wire_context) right after the header — the
# serving peer's span parents to the fetcher's span across the process
# boundary. Negotiated per request: untraced peers keep verb 1
# byte-for-byte.
_EXC_GET_TRACED = 3
_EXC_OK = 0
_EXC_RETRY = 1  # partition not (yet) published here: retryable
_EXC_ERR = 2


def exchange_mode() -> str:
    """``SRJT_EXCHANGE_MODE``: ``mesh`` (default — the in-process
    ``lax.all_to_all`` fast path) or ``tcp`` (cross-process
    ``TcpExchange`` framing). Consulted by callers that choose a
    transport — the ``--exchange-worker`` harness and benchmarks; the
    in-library mesh collectives always use the collective and do not
    read this knob."""
    from ..utils import knobs

    # the typed accessor warns and keeps "mesh" on an unknown value
    return knobs.get_str("SRJT_EXCHANGE_MODE")


_EXC_BREAKER = None
_EXC_BREAKER_LOCK = threading.Lock()


def exchange_breaker():
    """Process-global breaker for the TCP exchange path (mirrors
    sidecar.breaker()): consecutive fetch failures open it and further
    fetches fast-fail retryably without paying a dial; a half-open
    probe after the cooldown restores the path. States land under
    ``shuffle.exchange.breaker.*``."""
    global _EXC_BREAKER
    if _EXC_BREAKER is None:
        with _EXC_BREAKER_LOCK:
            if _EXC_BREAKER is None:
                from ..utils.deadline import CircuitBreaker

                _EXC_BREAKER = CircuitBreaker("shuffle.exchange.breaker")
    return _EXC_BREAKER


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def _recv_exact_tcp(sock, n: int, deadline: float) -> bytes:
    """Read exactly n bytes under a whole-request deadline (the
    SupervisedClient._recv_deadline discipline: the socket timeout
    shrinks to the remaining budget each iteration)."""
    buf = bytearray()
    while len(buf) < n:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise socket_mod.timeout("exchange deadline exhausted")
        sock.settimeout(remaining)
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("exchange: peer closed")
        buf.extend(chunk)
    return bytes(buf)


class TcpExchange:
    """One runtime's end of the cross-process exchange: a server that
    publishes this rank's outgoing partitions (encoded once as
    columnar frames) and a fetch client that pulls this rank's
    incoming partitions from peers under deadline + retry + breaker +
    CRC.

    Keys are ``(epoch, part)`` — an epoch is one exchange round (query
    stage), ``part`` the destination rank. A fetch for a partition not
    yet published parks on a condition server-side (bounded) and then
    answers retryably, so peer startup races cost latency, never
    wrong answers. Chaos hooks: each served request crosses
    ``faultinj.maybe_inject("exchange.serve")`` (``crash``/``delay``
    kinds) and each response frame crosses
    ``faultinj.maybe_corrupt("exchange.frame", ...)`` AFTER encoding —
    exactly like a transport flipping bits under the CRC, which the
    decoder must catch."""

    def __init__(self, rank: int, bind: str = "127.0.0.1:0",
                 deadline_s: Optional[float] = None,
                 publish_wait_s: float = 10.0,
                 retain_epochs: Optional[int] = None):
        from ..utils import knobs

        self.rank = int(rank)
        if deadline_s is None:
            deadline_s = knobs.get_float("SRJT_EXCHANGE_TIMEOUT_SEC")
        self.deadline_s = float(deadline_s)
        self.publish_wait_s = float(publish_wait_s)
        if retain_epochs is None:
            retain_epochs = knobs.get_int("SRJT_EXCHANGE_RETAIN_EPOCHS")
        # publish() evicts everything older than the newest
        # `retain_epochs` distinct epochs: a long-lived runtime doing
        # one exchange round per query stage must not accumulate every
        # encoded partition forever, while a crashed peer's
        # respawn-republish window (the previous few epochs) stays
        # servable
        self.retain_epochs = max(int(retain_epochs), 1)
        self._frames: Dict[Tuple[int, int], bytes] = {}
        self._lock = threading.Lock()
        self._published = threading.Condition(self._lock)
        self._closed = False
        host, port = _parse_addr(bind)
        self._srv = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
        self._srv.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.address = "%s:%d" % self._srv.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"srjt-exchange-r{self.rank}",
        )
        self._accept_thread.start()

    # -- server side ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return  # closed
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn) -> None:
        from ..utils import tracing

        try:
            conn.settimeout(self.deadline_s)
            while True:
                try:
                    hdr = b""
                    while len(hdr) < _EXC_REQ.size:
                        chunk = conn.recv(_EXC_REQ.size - len(hdr))
                        if not chunk:
                            return
                        hdr += chunk
                except (OSError, socket_mod.timeout):
                    return
                magic, verb, epoch, part = _EXC_REQ.unpack(hdr)
                if magic != _EXC_MAGIC or verb not in (
                    _EXC_GET, _EXC_GET_TRACED,
                ):
                    conn.sendall(_EXC_RESP.pack(_EXC_ERR, 0))
                    return
                # srjt-trace (ISSUE 12): a traced GET carries the
                # 17-byte context right after the header — read it
                # unconditionally so the stream stays framed even when
                # tracing is disarmed on this side
                tctx = None
                if verb == _EXC_GET_TRACED:
                    try:
                        tb = b""
                        while len(tb) < tracing.TRACE_CTX_LEN:
                            chunk = conn.recv(tracing.TRACE_CTX_LEN - len(tb))
                            if not chunk:
                                return
                            tb += chunk
                    except (OSError, socket_mod.timeout):
                        return
                    tctx = tracing.decode_wire_context(tb)
                if tctx is not None and tracing.is_enabled():
                    # the serving peer's half of the cross-process
                    # trace: the wait-for-publish and the frame send
                    # parent to the fetcher's span, logged HERE
                    with tracing.remote_scope(*tctx):
                        with tracing.span(
                            "exchange.serve", epoch=int(epoch),
                            part=int(part), rank=self.rank,
                        ):
                            self._answer_get(conn, epoch, part)
                else:
                    self._answer_get(conn, epoch, part)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _answer_get(self, conn, epoch: int, part: int) -> None:
        """Answer one GET: wait (bounded) for the partition to publish,
        then send it — or a retryable not-yet-published status."""
        from ..utils import faultinj, metrics

        # chaos choke point: `crash` kills the serving process
        # mid-request (the peer sees a dead transport and
        # retries), `delay` models a slow peer
        if faultinj.is_enabled():
            faultinj.maybe_inject("exchange.serve")
        with self._published:
            end = time.monotonic() + self.publish_wait_s
            blob = self._frames.get((epoch, part))
            while blob is None and not self._closed:
                left = end - time.monotonic()
                if left <= 0:
                    break
                self._published.wait(left)
                blob = self._frames.get((epoch, part))
        if blob is None:
            conn.sendall(_EXC_RESP.pack(_EXC_RETRY, 0))
            return
        wire = blob
        if faultinj.is_enabled():
            # flips bytes AFTER the frame (and its CRCs) was
            # encoded — the fetcher's decode MUST catch it
            wire = faultinj.maybe_corrupt("exchange.frame", blob)
        conn.sendall(_EXC_RESP.pack(_EXC_OK, len(wire)) + wire)
        metrics.counter("shuffle.tcp.bytes_out").inc(len(wire))

    def publish(self, epoch: int, partitions: Dict[int, "Table"]) -> None:
        """Encode and expose this rank's outgoing partitions for
        ``epoch`` (one frame per destination rank, per-column CRC under
        the integrity gate). Idempotent per key — a respawned peer
        re-publishing identical deterministic partitions is a no-op."""
        from ..columnar import frames as frames_mod
        from ..utils import metrics

        encoded = {
            (int(epoch), int(part)): frames_mod.encode_table(t)
            for part, t in partitions.items()
        }
        evicted = 0
        with self._published:
            self._frames.update(encoded)
            epochs = sorted({e for e, _ in self._frames})
            for old in epochs[: max(len(epochs) - self.retain_epochs, 0)]:
                stale = [k for k in self._frames if k[0] == old]
                for k in stale:
                    del self._frames[k]
                evicted += len(stale)
            self._published.notify_all()
        metrics.counter("shuffle.tcp.published").inc(len(encoded))
        if evicted:
            metrics.counter("shuffle.tcp.frames_evicted").inc(evicted)

    def drop_epoch(self, epoch: int) -> int:
        """Release one exchange round's published frames (e.g. after
        every peer has fetched); returns the number dropped."""
        with self._published:
            stale = [k for k in self._frames if k[0] == int(epoch)]
            for k in stale:
                del self._frames[k]
        return len(stale)

    # -- fetch side ----------------------------------------------------------

    def _fetch_once(self, addr: str, epoch: int, part: int) -> "Table":
        """One fetch attempt — the unit the retry orchestrator re-runs.
        Transport faults and not-yet-published answers raise
        RetryableError; a frame whose bytes rotted raises retryable
        DataCorruption from the decoder; an exhausted query budget
        raises DeadlineExceeded (never a raw socket timeout)."""
        from ..columnar import frames as frames_mod
        from ..utils import deadline as deadline_mod, metrics
        from ..utils.errors import RetryableError

        d = deadline_mod.current()
        # adaptive fetch deadline (ISSUE 9): observed q99 × multiplier
        # once warm, clamped into [floor, SRJT_EXCHANGE_TIMEOUT_SEC] —
        # a hung peer is detected at straggler timescales, not the
        # static knob's; the query budget still clamps below
        budget_s, clamped = metrics.adaptive_timeout_s(
            "shuffle.tcp.fetch_lat_us", self.deadline_s
        )
        if clamped:
            metrics.registry().counter(
                "shuffle.tcp.adaptive_timeout_clamps"
            ).inc()
        if d is not None:
            d.check("tcp_exchange_fetch")
            budget_s = min(budget_s, max(d.remaining(), 1e-3))
        deadline = time.monotonic() + budget_s
        t0 = time.monotonic()
        lat_hist = metrics.registry().histogram("shuffle.tcp.fetch_lat_us")
        host, port = _parse_addr(addr)
        s = socket_mod.socket(socket_mod.AF_INET, socket_mod.SOCK_STREAM)
        try:
            s.settimeout(budget_s)
            # srjt-trace (ISSUE 12): a sampled active context rides the
            # request as the traced GET verb + 17-byte blob, so the
            # peer's serve span parents to this fetch across processes
            from ..utils import tracing

            tblob = tracing.wire_context()
            verb = _EXC_GET if tblob is None else _EXC_GET_TRACED
            try:
                s.connect((host, port))
                s.sendall(
                    _EXC_REQ.pack(_EXC_MAGIC, verb, epoch, part)
                    + (tblob or b"")
                )
                status, blen = _EXC_RESP.unpack(
                    _recv_exact_tcp(s, _EXC_RESP.size, deadline)
                )
                blob = _recv_exact_tcp(s, blen, deadline) if blen else b""
            except socket_mod.timeout as e:
                # record the timed-out elapsed as a latency sample so
                # an over-tight adaptive clamp self-corrects upward
                lat_hist.record((time.monotonic() - t0) * 1e6)
                if d is not None and d.done():
                    raise d.exceeded("tcp exchange fetch") from e
                raise RetryableError(
                    f"shuffle exchange: DEADLINE_EXCEEDED: fetch of "
                    f"(epoch {epoch}, part {part}) from {addr} exceeded "
                    f"{budget_s:g}s"
                ) from e
            except (ConnectionError, OSError) as e:
                raise RetryableError(
                    f"shuffle exchange: UNAVAILABLE: peer {addr} "
                    f"({e})"
                ) from e
        finally:
            s.close()
        if status == _EXC_RETRY:
            raise RetryableError(
                f"shuffle exchange: UNAVAILABLE: peer {addr} has not "
                f"published (epoch {epoch}, part {part}) yet"
            )
        if status != _EXC_OK:
            # _EXC_ERR means the peer rejected our magic/verb: a
            # misaddressed or version-skewed peer, deterministic on
            # every attempt — fail fast instead of burning the whole
            # retry budget on a config error (the transient cases are
            # _EXC_RETRY and the transport faults above)
            from ..utils.errors import FatalDeviceError

            raise FatalDeviceError(
                f"shuffle exchange: peer {addr} answered error status "
                f"{status} (protocol mismatch — wrong service or "
                "version-skewed peer?)"
            )
        lat_hist.record((time.monotonic() - t0) * 1e6)
        metrics.counter("shuffle.tcp.bytes_in").inc(len(blob))
        # decode verifies the frame header + every column CRC: a
        # tampered exchange is retryable DataCorruption, never rows
        return frames_mod.decode_table(blob, where="shuffle.exchange")

    def fetch(self, addr: str, epoch: int, part: int) -> "Table":
        """Pull one partition from ``addr`` under retry + breaker +
        deadline. Corruption and transport faults retry; exhaustion
        records a breaker failure and re-raises retryably (the caller's
        supervisor may respawn the peer and call again).

        srjt-trace (ISSUE 12): one ``exchange.fetch`` span per fetch
        covers every retry attempt; each attempt propagates the
        context to the serving peer (``_fetch_once``)."""
        from ..utils import tracing

        with tracing.span(
            "exchange.fetch", peer=addr, epoch=int(epoch), part=int(part)
        ):
            return self._fetch_impl(addr, epoch, part)

    def _fetch_impl(self, addr: str, epoch: int, part: int) -> "Table":
        from ..utils import metrics, retry
        from ..utils.errors import DeadlineExceeded, RetryableError

        br = exchange_breaker()
        if not br.allow():
            raise RetryableError(
                "shuffle exchange: UNAVAILABLE: exchange breaker open "
                f"(peer {addr})"
            )
        t0 = time.perf_counter()
        try:
            table = retry.call_with_retry(
                self._fetch_once, addr, epoch, part,
                op_name="tcp_exchange_fetch",
            )
        except DeadlineExceeded:
            br.record_failure(cause="deadline")
            raise
        except RetryableError:
            br.record_failure(cause="unavailable")
            raise
        except BaseException:
            br.abort_probe()
            raise
        br.record_success()
        metrics.counter("shuffle.tcp.fetches").inc()
        metrics.histogram("shuffle.tcp.fetch_us").record(
            (time.perf_counter() - t0) * 1e6
        )
        return table

    # -- the one-call partition exchange -------------------------------------

    def exchange_table(self, table: "Table", key_cols: Sequence[str],
                       peers: Dict[int, str], epoch: int = 0) -> "Table":
        """Hash-repartition ``table`` across this rank and ``peers``
        (rank -> "host:port", this rank excluded): rows of one key all
        land on hash(key) % world, whatever process they started in.
        Publishes the outgoing partitions, pulls this rank's partition
        from every peer, and returns the concatenation in rank order —
        a deterministic row order, so downstream aggregation is
        reproducible bit for bit."""
        from ..ops.copying import concatenate, slice_table

        world = len(peers) + 1
        ranks = sorted(set(peers) | {self.rank})
        if len(ranks) != world or ranks != list(range(world)):
            raise ValueError(
                f"exchange peers must cover ranks 0..{world - 1} "
                f"(got self={self.rank}, peers={sorted(peers)})"
            )
        partitioned, offsets = hash_partition(table, world, key_cols)
        bounds = list(offsets) + [partitioned.num_rows]
        parts = {
            p: slice_table(partitioned, bounds[p], bounds[p + 1])
            for p in range(world)
        }
        self.publish(epoch, {p: t for p, t in parts.items() if p != self.rank})
        # pull every peer's partition CONCURRENTLY (wall-clock = the
        # slowest peer, not the sum; a slow peer must not stall pulls
        # from peers already serving), then reassemble in rank order so
        # row order — and therefore downstream aggregation — stays
        # deterministic. contextvars.copy_context() carries the
        # caller's deadline scope into each fetch thread (retry arming
        # is module-global and inherits on its own).
        import contextvars

        fetched: Dict[int, "Table"] = {}
        errs: List[BaseException] = []

        def _pull(r: int, addr: str, ctx) -> None:
            try:
                fetched[r] = ctx.run(self.fetch, addr, epoch, self.rank)
            except BaseException as e:  # srjt-lint: allow-broad-except(thread-exit funnel: the joiner re-raises errs[0] after joining every fetch thread)
                errs.append(e)

        pulls = [
            threading.Thread(
                target=_pull, args=(r, peers[r], contextvars.copy_context())
            )
            for r in ranks
            if r != self.rank
        ]
        for t in pulls:
            t.start()
        for t in pulls:
            t.join()
        if errs:
            raise errs[0]
        received = []
        names = list(table.names)
        for r in ranks:
            if r == self.rank:
                received.append(parts[self.rank])
            else:
                # frames carry schema (dtypes/validity), not names —
                # the caller owns the naming, so re-apply its schema
                received.append(Table(fetched[r].columns, names))
        return concatenate(received)

    def close(self) -> None:
        with self._published:
            self._closed = True
            self._frames.clear()
            self._published.notify_all()
        try:
            self._srv.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# two-process harness: the CLI tests/test_data_plane.py and
# benchmarks/bench_pool.py spawn as the peer rank. Deterministic by
# construction (seeded data, integer-exact sums), so a respawned
# incarnation recomputes and republishes identical partitions — which
# is what makes a kill -9'd peer survivable by plain refetching.
# ---------------------------------------------------------------------------


def _demo_table(rows: int, seed: int, num_keys: int = 64) -> Table:
    """The harness's deterministic workload: int64 keys + int64 values
    (integer sums are associative bit-for-bit, so the distributed
    result is comparable to the single-process one exactly)."""
    import numpy as np  # noqa: F811  (module-level np is fine; explicit)

    from ..columnar import Column, Table as _Table
    from ..columnar.dtype import INT64

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, num_keys, rows).astype(np.int64)
    vals = rng.integers(-1000, 1000, rows).astype(np.int64)
    return _Table(
        [Column(INT64, data=jnp.asarray(keys)), Column(INT64, data=jnp.asarray(vals))],
        ["k", "v"],
    )


def _local_groupby_sum(table: Table) -> Table:
    """Exact int64 groupby (sum + count) over the harness table,
    sorted by key — the deterministic per-rank aggregation whose
    concatenation must be bit-identical to the single-process run."""
    import numpy as np

    from ..columnar import Column, Table as _Table
    from ..columnar.dtype import INT64

    keys = np.asarray(table.column("k").data)
    vals = np.asarray(table.column("v").data)
    uniq, inv = np.unique(keys, return_inverse=True)
    sums = np.zeros(len(uniq), np.int64)
    counts = np.zeros(len(uniq), np.int64)
    np.add.at(sums, inv, vals)
    np.add.at(counts, inv, 1)
    return _Table(
        [
            Column(INT64, data=jnp.asarray(uniq)),
            Column(INT64, data=jnp.asarray(sums)),
            Column(INT64, data=jnp.asarray(counts)),
        ],
        ["k", "s", "c"],
    )


def _shard_bounds(rows: int, world: int, rank: int) -> Tuple[int, int]:
    return rows * rank // world, rows * (rank + 1) // world


def spawn_exchange_peer(parent_addr: str, rows: int, seed: int, *,
                        rank: int = 1, world: int = 2,
                        extra_env: Optional[dict] = None,
                        ready_timeout_s: float = 180.0,
                        respawn_of=None):
    """Spawn one ``--exchange-worker`` peer process against
    ``parent_addr`` (rank 0) and wait for its READY handshake; returns
    ``(Popen, peer_address)``. The ONE owner of the spawn/handshake
    protocol — tests and benchmarks both go through it, so a change to
    the CLI flags or the READY line cannot drift between them. The
    child inherits this environment minus any armed fault-injection
    config (pass it back via ``extra_env`` to storm the peer on
    purpose), with retry armed. ``respawn_of`` is the Popen of a DEAD
    predecessor being replaced: the harness verifies it exited and
    emits the ``exchange.peer_respawn`` event itself — the artifact
    the premerge chaos gate asserts on, so it must come from the
    machinery that observed the death, never from a test's own
    assertion."""
    import subprocess
    import sys

    from ..utils.errors import FatalDeviceError

    env = dict(os.environ)
    env.pop("SRJT_FAULTINJ_CONFIG", None)
    env["SRJT_RETRY_ENABLED"] = "1"
    if extra_env:
        env.update(extra_env)
    runner = (
        "from spark_rapids_jni_tpu.parallel.shuffle import _main; "
        "import sys; sys.exit(_main())"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", runner,
         "--exchange-worker", "--rank", str(rank), "--world", str(world),
         "--rows", str(rows), "--seed", str(seed),
         "--peers", f"0={parent_addr}"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, text=True,
    )
    import select

    # select() on the RAW fd + os.read into our own line buffer so
    # ready_timeout_s is actually enforced: a child that wedges before
    # printing (jax init hang) must not park the parent in a
    # timeout-less readline, an EOF while the child lives means READY
    # can never arrive (fail fast, never busy-spin on empty reads),
    # and a READY line that lands in the same pipe chunk as an earlier
    # stdout line must still be seen — selecting on the buffered text
    # stream would never report it readable again (the data already
    # left the pipe) and a healthy peer would be killed at timeout
    fd = proc.stdout.fileno()
    buf = b""
    t_end = time.monotonic() + ready_timeout_s
    while True:
        nl = buf.find(b"\n")
        if nl >= 0:
            line, buf = buf[:nl], buf[nl + 1:]
            text = line.decode("utf-8", "replace")
            if text.startswith("SRJT_EXCHANGE_READY"):
                if respawn_of is not None and respawn_of.poll() is not None:
                    from ..utils import metrics

                    metrics.event(
                        "exchange.peer_respawn", rank=rank,
                        prev_rc=respawn_of.returncode,
                    )
                return proc, text.strip().split("addr=")[1]
            continue
        remaining = t_end - time.monotonic()
        if remaining <= 0:
            break
        readable, _, _ = select.select([fd], [], [], min(remaining, 0.5))
        if not readable:
            if proc.poll() is not None:
                raise FatalDeviceError(
                    f"exchange peer exited during startup rc={proc.returncode}"
                )
            continue
        chunk = os.read(fd, 65536)
        if not chunk:
            if proc.poll() is not None:
                raise FatalDeviceError(
                    f"exchange peer exited during startup rc={proc.returncode}"
                )
            proc.kill()
            proc.wait()
            raise FatalDeviceError(
                "exchange peer closed stdout before reporting ready"
            )
        buf += chunk
    proc.kill()
    proc.wait()
    raise FatalDeviceError(
        f"exchange peer never reported ready within {ready_timeout_s:g}s"
    )


def _exchange_worker_main(args) -> int:
    """Peer-rank process: build the deterministic shard, exchange hash
    partitions with rank 0, aggregate, publish the result table (epoch
    ``args.epoch + 1``, part = this rank), then park until stdin
    closes. Prints ``SRJT_EXCHANGE_READY addr=<host:port>`` once the
    server is up — the line the parent polls for. The worker IS the
    cross-process posture, so it defaults ``SRJT_EXCHANGE_MODE`` to
    ``tcp`` and refuses an explicit ``mesh`` (an operator forcing the
    in-process mode on a cross-process peer is a config error, not
    something to ignore)."""
    import sys

    from ..ops.copying import slice_table
    from ..utils import retry

    os.environ.setdefault("SRJT_EXCHANGE_MODE", "tcp")
    if exchange_mode() != "tcp":
        print(
            "exchange worker: SRJT_EXCHANGE_MODE must be 'tcp' for a "
            "cross-process peer (got 'mesh')",
            file=sys.stderr,
        )
        return 2

    peers = {}
    for spec in (args.peers or "").split(","):
        if not spec:
            continue
        r, _, addr = spec.partition("=")
        peers[int(r)] = addr
    ex = TcpExchange(args.rank, bind=args.bind)
    print(f"SRJT_EXCHANGE_READY addr={ex.address}", flush=True)
    table = _demo_table(args.rows, args.seed)
    lo, hi = _shard_bounds(args.rows, args.world, args.rank)
    shard = slice_table(table, lo, hi)
    with retry.enabled(max_attempts=40, base_delay_ms=25, max_delay_ms=250):
        local = ex.exchange_table(shard, ["k"], peers, epoch=args.epoch)
        result = _local_groupby_sum(local)
        ex.publish(args.epoch + 1, {args.rank: result})
        # park: serve fetches until the supervisor closes our stdin
        sys.stdin.read()
    ex.close()
    return 0


def _main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description="TCP exchange worker harness")
    ap.add_argument("--exchange-worker", action="store_true", required=True)
    ap.add_argument("--rank", type=int, default=1)
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--bind", default="127.0.0.1:0")
    ap.add_argument("--peers", default="", help="rank=host:port,...")
    return _exchange_worker_main(ap.parse_args())


if __name__ == "__main__":
    import sys

    sys.exit(_main())

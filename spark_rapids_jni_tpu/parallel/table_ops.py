"""Table-level distributed relational ops: the tier Spark's plugin
actually calls (SURVEY §2.9 shuffle + §2.8 relational surface, lifted
from the raw int-array APIs in distributed.py / join_distributed.py to
``columnar.Table`` in / ``columnar.Table`` out).

Design:
- **Strings ride the exchange as dictionary codes.** The ICI all_to_all
  framing is static-shape fixed-width (parallel/shuffle.py); a STRING
  column dictionary-encodes to int32 codes against a batch-global
  dictionary (vectorized np.unique over the padded byte matrix), the
  codes exchange like any int lane, and receivers decode with one device
  ragged gather. This is the "the rejection becomes an encode step"
  path; the dictionary itself is replicated (it is the low-cardinality
  side by construction).
- **Composite keys hash-join exactly.** Destination routing chains
  murmur3 across key lanes (Spark Murmur3Hash parity,
  distributed.py:_hash_dest_multi). The per-shard sorted-run join runs
  on a 64-bit chained hash of the key tuple and VERIFIES every
  candidate pair on the raw lanes, so hash collisions cost only output
  slots, never correctness.
- **Skew-aware capacity default** (VERDICT r1 weak #4): the per-
  destination bucket default is ``max(4 * per_shard / n_parts, 64)``
  (expected occupancy x4 headroom, floored for tiny shards), capped at
  ``per_shard`` — O(N/P) receive buffers per shard instead of O(N),
  with the existing overflow flag as the resize signal.
- Null semantics follow Spark: null keys form one group (they exchange
  with a validity lane joined into the key tuple); aggregates skip null
  values; joins never match null keys.

FLOAT64 columns aggregate EXACTLY on every backend: the u64 IEEE-bit
lanes ride the exchange untouched and the shard aggregator runs the
windowed integer accumulator (ops/f64acc) — distributed SUM/MEAN/
MIN/MAX on doubles are bit-identical to the single-chip exact path.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import TypeId
from ..ops import bitutils
from ..ops.hashing import murmur3_raw
from ..utils.dispatch import op_boundary
from ..utils.errors import FatalDeviceError
from .distributed import _hash_dest_multi
from .join_distributed import shard_join_pairs
from .shuffle import _bucketize
from ._smcache import cached_sm, shard_map

__all__ = [
    "dict_encode",
    "dict_decode",
    "default_capacity",
    "exchange_table",
    "distributed_groupby_table",
    "distributed_join_table",
]


def default_capacity(per_shard: int, n_parts: int) -> int:
    """Skew-aware per-destination bucket capacity."""
    return min(per_shard, max(4 * ((per_shard + n_parts - 1) // n_parts), 64))


def _pad_lanes(lanes: List[jnp.ndarray], n: int, n_parts: int):
    """Pad every lane to a mesh-divisible row count; returns (padded
    lanes, present lane). Padding rows carry present=False and are
    excluded from every downstream semantic (group segmentation, join
    matching, compaction) — the eager Table tier's occupancy framing."""
    pad = (-n) % n_parts
    present = jnp.concatenate([jnp.ones((n,), bool), jnp.zeros((pad,), bool)]) if pad else jnp.ones((n,), bool)
    if pad == 0:
        return list(lanes), present
    out = []
    for a in lanes:
        z = jnp.zeros((pad,) + a.shape[1:], a.dtype)
        out.append(jnp.concatenate([a, z]))
    return out, present


# ---------------------------------------------------------------------------
# string dictionary codec
# ---------------------------------------------------------------------------


class StringDictionary:
    """Batch-global sorted dictionary: host-built (np.unique), device-
    resident parts for the decode gather."""

    def __init__(self, lens: np.ndarray, chars: np.ndarray):
        self.lens_h = lens
        offs = np.zeros(len(lens) + 1, np.int64)
        np.cumsum(lens, out=offs[1:])
        self.offs = jnp.asarray(offs)
        self.lens = jnp.asarray(lens.astype(np.int32))
        self.chars = jnp.asarray(chars)

    def __len__(self) -> int:
        return len(self.lens_h)


def dict_encode(col: Column) -> Tuple[Column, StringDictionary]:
    """STRING column -> (INT32 code column, dictionary). Codes of null
    rows are 0 with validity preserved. Vectorized: one padded-matrix
    np.unique, no per-row Python."""
    if col.dtype.id != TypeId.STRING:
        raise ValueError("dict_encode takes a STRING column")
    offs = np.asarray(col.offsets)
    chars = np.asarray(col.chars)
    n = len(offs) - 1
    lens = (offs[1:] - offs[:-1]).astype(np.int32)
    L = max(int(lens.max()) if n else 1, 1)
    padded = np.zeros((n, L + 4), np.uint8)  # +4: length tiebreaker lane
    idx = offs[:-1, None] + np.arange(L)[None, :]
    inb = np.arange(L)[None, :] < lens[:, None]
    if chars.shape[0]:
        padded[:, :L] = np.where(inb, chars[np.clip(idx, 0, chars.shape[0] - 1)], 0)
    padded[:, L:] = lens[:, None].view(np.uint8).reshape(n, 4) if n else 0
    keyed = padded.view([("bytes", np.uint8, L + 4)]).reshape(n)
    uniq, inverse = np.unique(keyed, return_inverse=True)
    codes = inverse.astype(np.int32)

    u = uniq["bytes"].reshape(len(uniq), L + 4)
    u_lens = u[:, L:].copy().view(np.int32).reshape(-1)
    take = np.arange(L)[None, :] < u_lens[:, None]
    u_chars = u[:, :L][take]
    d = StringDictionary(u_lens, u_chars)
    return Column(dt.INT32, data=jnp.asarray(codes), validity=col.validity), d


def dict_decode(codes: jnp.ndarray, dictionary: StringDictionary, validity=None) -> Column:
    """INT32 codes -> STRING column via one device ragged gather."""
    from ..ops.bitutils import ragged_positions

    codes = jnp.clip(codes, 0, max(len(dictionary) - 1, 0))
    lens = dictionary.lens[codes] if len(dictionary) else jnp.zeros(codes.shape, jnp.int32)
    offs, row_of, pos, total = ragged_positions(lens)
    if total == 0:
        chars = jnp.zeros((0,), jnp.uint8)
    else:
        chars = dictionary.chars[dictionary.offs[codes[row_of]] + pos]
    return Column(dt.STRING, validity=validity, offsets=offs, chars=chars)


# ---------------------------------------------------------------------------
# Table <-> lane decomposition (what actually rides the exchange)
# ---------------------------------------------------------------------------


def _col_lanes(col: Column):
    """Column -> (data_lane, validity_lane_or_None, meta) where meta
    rebuilds the column after the exchange."""
    tid = col.dtype.id
    if tid == TypeId.STRING:
        codes, d = dict_encode(col)
        return codes.data, col.validity, ("string", d)
    if tid in (TypeId.LIST, TypeId.STRUCT):
        raise ValueError("nested columns: exchange leaf lanes individually")
    return col.data, col.validity, ("fixed", col.dtype)


def _rebuild(meta, data, validity) -> Column:
    kind, aux = meta
    if kind == "string":
        return dict_decode(data, aux, validity=validity)
    return Column(aux, data=data, validity=validity)


@op_boundary("exchange_table")
def exchange_table(
    table: Table,
    key_cols: Sequence[str],
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
) -> Tuple[Table, bool]:
    """Hash-repartition a row-sharded Table (strings included) over the
    mesh; returns the received rows as a compacted global Table plus the
    overflow flag. Rows of equal key tuples land on one shard."""
    n_parts = mesh.shape[axis]
    n = table.num_rows

    lanes: List[jnp.ndarray] = []
    metas = []
    has_v: List[bool] = []
    lane_pos: List[int] = []  # data-lane index per column
    for c in table.columns:
        data, validity, meta = _col_lanes(c)
        lane_pos.append(len(lanes))
        lanes.append(data)
        metas.append(meta)
        has_v.append(validity is not None)
        if validity is not None:
            lanes.append(validity)

    lanes, present = _pad_lanes(lanes, n, n_parts)
    per_shard = present.shape[0] // n_parts
    if capacity is None:
        capacity = default_capacity(per_shard, n_parts)

    # memory tier: refuse buffer footprints past the device budget
    # BEFORE dispatch (retryable — the caller splits or the task
    # re-runs), instead of letting XLA OOM with a possibly poisoned
    # client (utils/memory.py)
    from ..utils.memory import (
        MemoryBudgetExceeded,
        device_memory_budget,
        exchange_bytes_estimate,
    )

    row_bytes = 8 * len(lanes)  # flat upper bound: every lane <= 8B
    est = exchange_bytes_estimate(row_bytes, n_parts, int(capacity))
    budget = device_memory_budget()
    if est > budget:
        raise MemoryBudgetExceeded(
            f"exchange at capacity {capacity} needs ~{est} device bytes "
            f"(budget {budget}); split the batch or lower the capacity"
        )

    # keys are derived INSIDE the body from the payload lanes at these
    # positions (no duplicate key operands through shard_map); null
    # rows' garbage data is masked to 0 so every null key hashes
    # identically, and the validity lane joins the hash chain so null
    # keys co-locate
    key_pos = []
    for k in key_cols:
        ki = table.names.index(k)
        key_pos.append((lane_pos[ki], lane_pos[ki] + 1 if has_v[ki] else None))

    def body(*arrs):
        pres, payload = arrs[0], arrs[1:]
        ks = []
        for dpos, vpos in key_pos:
            data = payload[dpos]
            if vpos is not None:
                validity = payload[vpos]
                ks.append(jnp.where(validity, data, jnp.zeros((), data.dtype)))
                ks.append(validity.astype(jnp.int32))
            else:
                ks.append(data)
        dest = _hash_dest_multi(ks, n_parts)
        a2a = lambda x: lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
        outs = []
        ovf = jnp.zeros((), bool)
        mask = None
        for a in (pres,) + tuple(payload):
            b, m, o = _bucketize(a, dest, n_parts, capacity)
            outs.append(a2a(b).reshape((-1,) + a.shape[1:]))
            ovf = ovf | o
            mask = m
        rm = a2a(mask).reshape(-1) & outs[0]  # occupied AND real row
        return tuple(outs[1:]) + (rm, ovf[None])

    spec = P(axis)
    f = cached_sm(
        ("exchange_table", mesh, axis, int(capacity), len(lanes),
         tuple(str(a.dtype) for a in lanes),
         tuple(key_pos), tuple(has_v)),  # body statics: which lanes hash as keys
        lambda: jax.jit(shard_map(
            body,
            mesh=mesh,
            in_specs=(spec,) * (1 + len(lanes)),
            out_specs=(spec,) * (len(lanes) + 2),
        )),
    )
    *received, recv_mask, ovf = f(present, *lanes)

    # compact received slots (host boundary of the eager op tier)
    keep = np.asarray(recv_mask)
    sel = jnp.asarray(np.flatnonzero(keep))
    cols = []
    it = iter(received)
    for meta, nullable in zip(metas, has_v):
        data = next(it)[sel]
        validity = next(it)[sel] if nullable else None
        cols.append(_rebuild(meta, data, validity))
    return Table(cols, names=list(table.names)), bool(np.asarray(ovf).any())


# ---------------------------------------------------------------------------
# distributed groupby on Tables
# ---------------------------------------------------------------------------

_AGG_HOWS = ("sum", "count", "min", "max", "mean")


def _value_lane(col: Column) -> jnp.ndarray:
    """Aggregate-value lane. FLOAT64 stays in its u64 IEEE-bit storage —
    the shard aggregator runs the EXACT windowed integer accumulator on
    it (ops/f64acc), so distributed sums/means/extrema are bit-identical
    to the single-chip exact path (no f32 hop; VERDICT r3 item 5)."""
    return col.data


def _shard_groupby_aggs(key_arrays, val_arrays, hows, present, val_present, capacity: int,
                        f64_flags=None):
    """Static-shape multi-aggregate groupby (shard-local). Returns
    (key_arrays[capacity], agg_arrays, agg_valid_arrays, group_valid,
    overflow). An aggregate over a group whose values are ALL null is
    itself null (Spark) — agg_valid carries that; count is the
    exception (0, always valid)."""
    order = jnp.lexsort(tuple(reversed(list(key_arrays))) + (~present,))
    ks = [k[order] for k in key_arrays]
    ps = present[order]

    changed = jnp.zeros((ks[0].shape[0] - 1,), bool)
    for k in ks:
        changed = changed | (k[1:] != k[:-1])
    new_seg = jnp.concatenate([jnp.ones((1,), bool), changed]) & ps
    seg = jnp.cumsum(new_seg).astype(jnp.int32) - 1
    num_groups = jnp.maximum(seg[-1] + 1, 0)
    overflow = num_groups > capacity
    seg = jnp.where(ps, jnp.clip(seg, 0, capacity - 1), capacity)

    if f64_flags is None:
        f64_flags = [False] * len(val_arrays)
    aggs = []
    agg_valid = []
    for v, how, vp, is_f64bits in zip(val_arrays, hows, val_present, f64_flags):
        # is_f64bits comes from the COLUMN dtype (FLOAT64 IEEE-bit lane)
        # — never inferred from the jnp dtype, which a genuine UINT64
        # integer column shares
        vs = v[order]
        vps = (ps & vp[order]) if vp is not None else ps
        cnt = jax.ops.segment_sum(vps.astype(jnp.int64), seg, num_segments=capacity + 1)[:capacity]
        if how in ("sum", "mean"):
            if is_f64bits:
                from ..ops.f64acc import segment_mean_f64bits, segment_sum_f64bits

                if how == "sum":
                    s = segment_sum_f64bits(vs, seg, capacity + 1, valid=vps)[:capacity]
                else:
                    s, _c = segment_mean_f64bits(vs, seg, capacity + 1, valid=vps)
                    s = s[:capacity]
                aggs.append(s)
            else:
                x = jnp.where(vps, vs, 0)
                is_u64 = x.dtype == jnp.uint64
                if is_u64:
                    # same two's-complement sum bits (mod 2^64); the
                    # mean re-reads them unsigned
                    x = lax.bitcast_convert_type(x, jnp.int64)
                elif jnp.issubdtype(x.dtype, jnp.integer):
                    x = x.astype(jnp.int64)
                s = jax.ops.segment_sum(x, seg, num_segments=capacity + 1)[:capacity]
                if how == "sum":
                    aggs.append(
                        lax.bitcast_convert_type(s, jnp.uint64) if is_u64 else s
                    )
                elif jnp.issubdtype(vs.dtype, jnp.integer):
                    # exact int mean: limb-divide the exact int64 sum
                    from ..ops.f64acc import mean_i64_div

                    if is_u64:
                        aggs.append(
                            mean_i64_div(
                                lax.bitcast_convert_type(s, jnp.uint64), cnt, unsigned=True
                            )
                        )
                    else:
                        aggs.append(mean_i64_div(s, cnt))
                else:
                    aggs.append(s / jnp.maximum(cnt, 1).astype(s.dtype))
            agg_valid.append(cnt > 0)
        elif how == "count":
            aggs.append(cnt)
            agg_valid.append(jnp.ones((capacity,), bool))
        elif how in ("min", "max"):
            if is_f64bits:
                # exact total-order comparison on the stored bits
                from jax import lax as _lax

                from ..ops import bitutils as _bt
                from ..ops.aggregate import _from_total_order
                from ..columnar import dtype as _dt

                key = _bt.total_order_key(vs, _dt.FLOAT64)
                k = _lax.bitcast_convert_type(key ^ jnp.uint64(1 << 63), jnp.int64)
                fill = jnp.iinfo(jnp.int64).max if how == "min" else jnp.iinfo(jnp.int64).min
                f = jax.ops.segment_min if how == "min" else jax.ops.segment_max
                r = f(jnp.where(vps, k, fill), seg, num_segments=capacity + 1)[:capacity]
                key_back = _lax.bitcast_convert_type(r, jnp.uint64) ^ jnp.uint64(1 << 63)
                aggs.append(_from_total_order(key_back, _dt.FLOAT64))
            else:
                if jnp.issubdtype(vs.dtype, jnp.integer):
                    fill = jnp.iinfo(vs.dtype).max if how == "min" else jnp.iinfo(vs.dtype).min
                else:
                    fill = jnp.inf if how == "min" else -jnp.inf
                x = jnp.where(vps, vs, fill)
                f = jax.ops.segment_min if how == "min" else jax.ops.segment_max
                aggs.append(f(x, seg, num_segments=capacity + 1)[:capacity])
            agg_valid.append(cnt > 0)
        else:
            raise ValueError(f"unknown agg {how!r} (supported: {_AGG_HOWS})")

    out_keys = [
        jnp.zeros((capacity,), k.dtype).at[seg].set(kk, mode="drop")
        for k, kk in zip(key_arrays, ks)
    ]
    group_valid = jnp.arange(capacity, dtype=jnp.int32) < num_groups
    return out_keys, aggs, agg_valid, group_valid, overflow


@op_boundary("distributed_groupby_table")
def distributed_groupby_table(
    table: Table,
    key_cols: Sequence[str],
    aggs: Sequence[Tuple[str, str, str]],  # (value_col, how, out_name)
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    group_capacity: Optional[int] = None,
) -> Tuple[Table, bool]:
    """GROUP BY key_cols with multiple aggregates across the mesh —
    Table in, compacted Table out (keys + one column per aggregate).
    String keys group via dictionary codes and decode on the way out.
    One compiled program end-to-end; host touches only the compaction.
    Defaulted capacities recompute 4x larger on overflow (once).
    """
    for _v, how, _o in aggs:
        if how not in _AGG_HOWS:
            raise ValueError(f"unknown agg {how!r}")
    n_parts = mesh.shape[axis]
    n_global = table.num_rows
    per_shard = (n_global + n_parts - 1) // n_parts
    auto = capacity is None and group_capacity is None
    if capacity is None:
        capacity = default_capacity(max(per_shard, 1), n_parts)
    if group_capacity is None:
        group_capacity = min(capacity * n_parts, max(per_shard, 64))
    # memory tier guards the FIRST dispatch too: a batch whose default
    # capacity already exceeds the budget must split, not OOM
    from ..utils.memory import device_memory_budget, exchange_bytes_estimate

    row_bytes = _exchange_row_bytes(table, key_cols, aggs)
    if auto and exchange_bytes_estimate(row_bytes, n_parts, int(capacity)) > device_memory_budget():
        return _groupby_split_retry(table, key_cols, aggs, mesh, axis)
    out = _groupby_once(table, key_cols, aggs, mesh, axis, int(capacity), int(group_capacity))
    if out[1] and auto:
        capacity = max(per_shard, 1)
        # same budget check for the escalated capacity: a skewed key
        # must not grow buckets until XLA OOMs — split instead (the
        # reference's 2 GiB batching discipline), merging partials
        if exchange_bytes_estimate(row_bytes, n_parts, capacity) > device_memory_budget():
            return _groupby_split_retry(table, key_cols, aggs, mesh, axis)
        out = _groupby_once(
            table, key_cols, aggs, mesh, axis, capacity, capacity * n_parts
        )
    return out


def _exchange_row_bytes(table: Table, key_cols: Sequence[str], aggs) -> int:
    """Bytes per exchanged row for the groupby shuffle: 8B upper bound
    per lane, two lanes (data + possible validity) per key and per
    aggregate value."""
    return 16 * (len(key_cols) + len(aggs))


_MERGE_HOW = {"sum": "sum", "count": "sum", "count_all": "sum", "min": "min", "max": "max"}


def _groupby_split_retry(
    table: Table,
    key_cols: Sequence[str],
    aggs: Sequence[Tuple[str, str, str]],
    mesh: Mesh,
    axis: str,
) -> Tuple[Table, bool]:
    """Split the batch in half row-wise, run each half (recursively
    subject to the same budget), and re-aggregate the partial results
    on a single chip. ``mean`` decomposes into sum+count for the
    partials and recombines at the end; every other supported aggregate
    is merge-associative."""
    from ..ops.aggregate import groupby_aggregate
    from ..ops.copying import slice_table
    from ..utils.memory import _note_split

    _note_split()
    n = table.num_rows
    if n < 2:
        # halving cannot go below one row: retrying is unproductive,
        # so this must NOT be retryable (taxonomy: fatal ends the
        # split recursion instead of burning the attempt budget)
        raise FatalDeviceError("cannot split a single-row batch further")
    # mean is not merge-associative: compute sum + count in the partials
    inner_aggs: List[Tuple[str, str, str]] = []
    for vname, how, oname in aggs:
        if how == "mean":
            inner_aggs.append((vname, "sum", f"{oname}__s"))
            inner_aggs.append((vname, "count", f"{oname}__c"))
        else:
            inner_aggs.append((vname, how, oname))

    mid = (n // 2 + mesh.shape[axis] - 1) // mesh.shape[axis] * mesh.shape[axis]
    mid = min(max(mid, 1), n - 1)
    parts = []
    for lo, hi in ((0, mid), (mid, n)):
        half = slice_table(table, lo, hi)
        out, ovf = distributed_groupby_table(half, key_cols, inner_aggs, mesh, axis=axis)
        if ovf:
            # a half that still overflows after its own escalation/split
            # cannot produce the caller's schema from here — surface the
            # retryable pressure instead of a partial with alien columns
            from ..utils.memory import MemoryBudgetExceeded

            raise MemoryBudgetExceeded(
                "groupby split-retry: half-batch still overflows its capacity"
            )
        parts.append(out)

    from ..ops.copying import concatenate

    merged_in = concatenate(parts)
    keys_t = Table([merged_in.column(k) for k in key_cols], list(key_cols))
    val_names = [o for _v, _h, o in inner_aggs]
    vals_t = Table([merged_in.column(o) for o in val_names], val_names)
    merge_aggs = [(o, _MERGE_HOW[h]) for (_v, h, o) in inner_aggs]
    merged = groupby_aggregate(keys_t, vals_t, merge_aggs)

    out_cols = [merged.column(k) for k in key_cols]
    out_names = list(key_cols)
    for vname, how, oname in aggs:
        if how == "mean":
            s = merged.column(f"{oname}__s_sum")
            c = merged.column(f"{oname}__c_sum")
            valid = c.data > 0
            if s.validity is not None:
                valid = valid & s.validity
            if s.dtype.id == TypeId.FLOAT64:
                # exact recombination: merged partial-sum bits / count
                from ..ops.f64acc import div_f64bits_by_int

                mbits = div_f64bits_by_int(s.data, jnp.maximum(c.data, 1))
                out_cols.append(Column(dt.FLOAT64, data=mbits, validity=valid))
            elif jnp.issubdtype(s.data.dtype, jnp.integer):
                from ..ops.f64acc import mean_i64_div

                mbits = mean_i64_div(s.data.astype(jnp.int64), jnp.maximum(c.data, 1))
                out_cols.append(Column(dt.FLOAT64, data=mbits, validity=valid))
            else:
                # FLOAT32 partials divide in their own float lane
                m = s.data / jnp.maximum(c.data, 1).astype(s.data.dtype)
                out_cols.append(
                    Column(
                        dt.FLOAT64,
                        data=bitutils.float_store(m, dt.FLOAT64),
                        validity=valid,
                    )
                )
        else:
            mcol = merged.column(f"{oname}_{_MERGE_HOW[how]}")
            out_cols.append(mcol)
        out_names.append(oname)
    return Table(out_cols, out_names), False



def _groupby_once(
    table: Table,
    key_cols: Sequence[str],
    aggs: Sequence[Tuple[str, str, str]],
    mesh: Mesh,
    axis: str,
    capacity: int,
    group_capacity: int,
) -> Tuple[Table, bool]:
    n_parts = mesh.shape[axis]
    n_global = table.num_rows
    cap_g = int(group_capacity)

    # key lanes: data (+ validity as an extra lane so null keys form
    # their own group and route to one shard)
    key_metas = []
    key_lanes: List[jnp.ndarray] = []
    key_lane_of: List[Tuple[int, bool]] = []  # (lane index, is_validity)
    for kname in key_cols:
        col = table.column(kname)
        data, validity, meta = _col_lanes(col)
        key_metas.append(meta)
        key_lane_of.append((len(key_lanes), validity is not None))
        key_lanes.append(jnp.where(validity, data, jnp.zeros((), data.dtype)) if validity is not None else data)
        if validity is not None:
            key_lanes.append(validity.astype(jnp.int32))

    val_lanes: List[jnp.ndarray] = []
    val_valid: List[Optional[jnp.ndarray]] = []
    hows: List[str] = []
    f64_flags: List[bool] = []
    out_meta: List[Tuple[str, str]] = []
    for vname, how, oname in aggs:
        col = table.column(vname)
        if col.dtype.id == TypeId.STRING:
            raise ValueError("aggregating STRING columns is not supported")
        val_lanes.append(_value_lane(col))
        val_valid.append(col.validity)
        hows.append(how)
        f64_flags.append(col.dtype.id == TypeId.FLOAT64)
        out_meta.append((oname, how))
    n_keys = len(key_lanes)
    n_vals = len(val_lanes)
    valid_lanes = [v for v in val_valid if v is not None]
    all_lanes, present = _pad_lanes(
        key_lanes + val_lanes + valid_lanes, n_global, n_parts
    )
    key_lanes = all_lanes[:n_keys]
    val_lanes = all_lanes[n_keys : n_keys + n_vals]
    valid_lanes = all_lanes[n_keys + n_vals :]

    def body(*arrs):
        ks = list(arrs[:n_keys])
        pres = arrs[n_keys]
        vs = list(arrs[n_keys + 1 : n_keys + 1 + n_vals])
        vps = list(arrs[n_keys + 1 + n_vals :])
        dest = _hash_dest_multi(ks, n_parts)
        a2a = lambda x: lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
        ovf = jnp.zeros((), bool)
        kr = []
        mask = None
        for k in ks:
            b, m, o = _bucketize(k, dest, n_parts, capacity)
            kr.append(a2a(b).reshape(-1))
            ovf, mask = ovf | o, m
        pb, _, _ = _bucketize(pres, dest, n_parts, capacity)
        pr = a2a(pb).reshape(-1)
        vr = []
        for v in vs:
            b, _, _ = _bucketize(v, dest, n_parts, capacity)
            vr.append(a2a(b).reshape(-1))
        vpr = []
        for vp in vps:
            b, _, _ = _bucketize(vp, dest, n_parts, capacity)
            vpr.append(a2a(b).reshape(-1))
        mr = a2a(mask).reshape(-1) & pr
        # re-thread optional validity lanes
        vp_full: List[Optional[jnp.ndarray]] = []
        j = 0
        for orig in val_valid:
            if orig is not None:
                vp_full.append(vpr[j])
                j += 1
            else:
                vp_full.append(None)
        gks, gas, gavs, gv, ovf2 = _shard_groupby_aggs(
            kr, vr, hows, mr, vp_full, cap_g, f64_flags=f64_flags
        )
        return (
            tuple(gk[None] for gk in gks)
            + tuple(a[None] for a in gas)
            + tuple(av[None] for av in gavs)
            + (gv[None], (ovf | ovf2)[None])
        )

    spec = P(axis)
    f = cached_sm(
        ("gb_table", mesh, axis, int(capacity), cap_g, n_keys, n_vals,
         tuple(hows), tuple(f64_flags), tuple(v is not None for v in val_valid),
         tuple(str(a.dtype) for a in key_lanes + val_lanes)),
        lambda: jax.jit(shard_map(
            body,
            mesh=mesh,
            in_specs=(spec,) * (n_keys + 1 + n_vals + len(valid_lanes)),
            out_specs=(spec,) * (n_keys + 2 * n_vals + 2),
        )),
    )
    outs = f(*key_lanes, present, *val_lanes, *valid_lanes)
    gks = outs[:n_keys]
    gas = outs[n_keys : n_keys + n_vals]
    gavs = outs[n_keys + n_vals : n_keys + 2 * n_vals]
    gv = np.asarray(outs[n_keys + 2 * n_vals]).reshape(-1)
    ovf = bool(np.asarray(outs[n_keys + 2 * n_vals + 1]).any())

    sel = jnp.asarray(np.flatnonzero(gv))
    cols: List[Column] = []
    names: List[str] = []
    li = 0
    for kname, meta, (lane, nullable) in zip(key_cols, key_metas, key_lane_of):
        data = jnp.asarray(gks[li]).reshape(-1)[sel]
        li += 1
        validity = None
        if nullable:
            validity = jnp.asarray(gks[li]).reshape(-1)[sel].astype(bool)
            li += 1
        cols.append(_rebuild(meta, data, validity))
        names.append(kname)
    sel_np = np.flatnonzero(gv)
    # ONE host transfer for every aggregate's validity lane (K separate
    # np.asarray pulls would block once per aggregate on a remote
    # backend); nulls re-upload only for the rare all-null-group case
    gavs_h = jax.device_get(list(gavs))
    for (oname, how), g, gav_h, (vname, _h, _o) in zip(out_meta, gas, gavs_h, aggs):
        arr = jnp.asarray(g).reshape(-1)[sel]
        av_np = gav_h.reshape(-1)[sel_np]
        validity = None if av_np.all() else jnp.asarray(av_np)
        src = table.column(vname)
        src_is_f64 = src.dtype.id == TypeId.FLOAT64
        # exact paths return ready-made FLOAT64 IEEE bits: every agg of
        # a FLOAT64 column, and the exact integer mean (mean_i64_div) —
        # keyed off the COLUMN dtype, never the lane dtype (a genuine
        # UINT64 min/max result is an integer that happens to be u64)
        if (src_is_f64 and how in ("sum", "mean", "min", "max")) or (
            how == "mean" and jnp.issubdtype(src.data.dtype, jnp.integer)
        ):
            cols.append(Column(dt.FLOAT64, data=arr, validity=validity))
        elif how == "mean":
            cols.append(Column(dt.FLOAT64, data=bitutils.float_store(arr, dt.FLOAT64), validity=validity))
        elif how == "count":
            cols.append(Column(dt.INT64, data=arr))
        elif arr.dtype == jnp.uint64 and how == "sum":
            cols.append(Column(dt.UINT64, data=arr, validity=validity))
        elif jnp.issubdtype(arr.dtype, jnp.integer) and how == "sum":
            cols.append(Column(dt.INT64, data=arr.astype(jnp.int64), validity=validity))
        else:
            cols.append(Column(src.dtype, data=arr, validity=validity))
        names.append(oname)
    return Table(cols, names=names), ovf


# ---------------------------------------------------------------------------
# distributed join on Tables
# ---------------------------------------------------------------------------


def _hash64(key_arrays) -> jnp.ndarray:
    """64-bit chained murmur over the key tuple (two independent seeds);
    collisions are verified away pair-by-pair, so this only routes."""
    h1 = None
    h2 = None
    for k in key_arrays:
        h1 = murmur3_raw(k) if h1 is None else murmur3_raw(k, seed=h1)
        h2 = murmur3_raw(k, seed=jnp.uint32(0x9E3779B9)) if h2 is None else murmur3_raw(k, seed=h2)
    lo = h1.astype(jnp.uint64)
    hi = h2.astype(jnp.uint64)
    return lax.bitcast_convert_type((hi << 32) | lo, jnp.int64)


@op_boundary("distributed_join_table")
def distributed_join_table(
    left: Table,
    right: Table,
    on: Sequence[str],
    mesh: Mesh,
    how: str = "inner",
    axis: str = "data",
    capacity: Optional[int] = None,
    out_capacity: Optional[int] = None,
    max_retries: int = 2,
) -> Tuple[Table, bool]:
    """Shuffled hash join on Tables across the mesh: `how` in
    {inner, left_semi, left_anti}. Composite keys route by chained
    murmur3 and match on a verified 64-bit hash run; string key/payload
    columns travel as dictionary codes. Null keys never match (Spark).

    Output: inner -> left columns + right non-key columns; semi/anti ->
    left columns. Compacted global Table + overflow flag.

    Capacities default skew-aware (O(N/P) buffers); on overflow with
    defaulted capacities the join recomputes with 4x larger buffers
    (up to `max_retries` times) before surfacing the flag.
    """
    if how not in ("inner", "left_semi", "left_anti"):
        raise ValueError(f"how={how!r} not supported (inner/left_semi/left_anti)")
    n_parts = mesh.shape[axis]
    per_l = (left.num_rows + n_parts - 1) // n_parts
    per_r = (right.num_rows + n_parts - 1) // n_parts
    auto = capacity is None and out_capacity is None
    if capacity is None:
        capacity = max(
            default_capacity(max(per_l, 1), n_parts),
            default_capacity(max(per_r, 1), n_parts),
        )
    if out_capacity is None:
        out_capacity = (
            max(per_l, 64) if how != "inner" else max(2 * max(per_l, per_r), 64)
        )
    for _attempt in range(max_retries + 1):
        table, ovf = _join_once(
            left, right, on, mesh, how, axis, int(capacity), int(out_capacity)
        )
        if not ovf or not auto:
            return table, ovf
        capacity = min(capacity * 4, max(per_l, per_r, 1))
        out_capacity *= 4
    return table, ovf


def _join_once(
    left: Table,
    right: Table,
    on: Sequence[str],
    mesh: Mesh,
    how: str,
    axis: str,
    capacity: int,
    out_capacity: int,
) -> Tuple[Table, bool]:
    n_parts = mesh.shape[axis]
    cap_out = int(out_capacity)

    # STRING join keys need ONE dictionary spanning both tables (codes
    # from independent encodes would never compare equal): encode the
    # concatenated column, split the codes back per side.
    shared: dict = {}
    for name in on:
        lc, rc = left.column(name), right.column(name)
        if lc.dtype.id == TypeId.STRING or rc.dtype.id == TypeId.STRING:
            if lc.dtype.id != rc.dtype.id:
                raise ValueError(f"join key {name!r} has mismatched types")
            both = Column(
                dt.STRING,
                validity=None,
                offsets=jnp.concatenate(
                    [lc.offsets, rc.offsets[1:] + lc.offsets[-1]]
                ),
                chars=jnp.concatenate([lc.chars, rc.chars]),
            )
            codes, d = dict_encode(both)
            nl = len(lc)
            shared[name] = (codes.data[:nl], codes.data[nl:], d)

    def lanes_of(tbl: Table, side: int):
        lanes, metas, has_v = [], [], []
        for nm, c in zip(tbl.names, tbl.columns):
            if nm in shared:
                data = shared[nm][side]
                validity, meta = c.validity, ("string", shared[nm][2])
            else:
                data, validity, meta = _col_lanes(c)
            lanes.append(data)
            metas.append(meta)
            has_v.append(validity is not None)
            if validity is not None:
                lanes.append(validity)
        return lanes, metas, has_v

    l_lanes, l_metas, l_hasv = lanes_of(left, 0)
    r_lanes, r_metas, r_hasv = lanes_of(right, 1)

    def key_positions(tbl, has_v):
        # (data lane idx, validity lane idx or None) per key column —
        # key lanes ride the exchange ONCE, inside the payload; both the
        # routing hash (pre-exchange) and the collision verification
        # (post-exchange) index the payload lanes at these positions
        out = []
        for name in on:
            i = tbl.names.index(name)
            lane_pos = sum(1 + int(h) for h in has_v[:i])
            out.append((lane_pos, lane_pos + 1 if has_v[i] else None))
        return out

    l_kpos = key_positions(left, l_hasv)
    r_kpos = key_positions(right, r_hasv)
    n_on = len(on)

    # pad each side to a mesh-divisible row count (present=False rows
    # never match and never survive compaction)
    l_lanes, l_present = _pad_lanes(l_lanes, left.num_rows, n_parts)
    r_lanes, r_present = _pad_lanes(r_lanes, right.num_rows, n_parts)
    nl_lanes, nr_lanes = len(l_lanes), len(r_lanes)

    def keys_from(lanes, kpos):
        ks, null_mask = [], None
        for dpos, vpos in kpos:
            ks.append(lanes[dpos])
            if vpos is not None:
                v = lanes[vpos].astype(bool)
                null_mask = v if null_mask is None else (null_mask & v)
        return ks, null_mask

    def body(*arrs):
        lpres, rpres = arrs[0], arrs[1]
        lps = list(arrs[2 : 2 + nl_lanes])
        rps = list(arrs[2 + nl_lanes :])
        lks, lkv = keys_from(lps, l_kpos)
        rks, rkv = keys_from(rps, r_kpos)

        ld = _hash_dest_multi(lks, n_parts)
        rd = _hash_dest_multi(rks, n_parts)
        a2a = lambda x: lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)

        def exchange(arr_list, dest):
            outs, mask, ovf = [], None, jnp.zeros((), bool)
            for a in arr_list:
                b, m, o = _bucketize(a, dest, n_parts, capacity)
                outs.append(a2a(b).reshape((-1,) + a.shape[1:]))
                mask, ovf = m, ovf | o
            rm = a2a(mask).reshape(-1)
            return outs, rm, ovf

        lh = _hash64(lks)
        rh = _hash64(rks)
        l_all, lm, o1 = exchange([lh, lpres] + lps, ld)
        r_all, rm, o2 = exchange([rh, rpres] + rps, rd)
        lh_r, lpres_r, lps_r = l_all[0], l_all[1], l_all[2:]
        rh_r, rpres_r, rps_r = r_all[0], r_all[1], r_all[2:]
        lks_r, lkv_r = keys_from(lps_r, l_kpos)
        rks_r, rkv_r = keys_from(rps_r, r_kpos)

        lm = lm & lpres_r
        rm = rm & rpres_r
        lpresent = lm if lkv_r is None else (lm & lkv_r)
        rpresent = rm if rkv_r is None else (rm & rkv_r)
        li, ri, pv, o3 = shard_join_pairs(lh_r, lpresent, rh_r, rpresent, cap_out)
        # verify raw key equality (hash collisions only shed here)
        for a, b in zip(lks_r, rks_r):
            pv = pv & (a[li] == b[ri])

        def wsel(mask, arr):  # mask rows, broadcast over trailing dims
            m = mask.reshape(mask.shape + (1,) * (arr.ndim - 1))
            return jnp.where(m, arr, jnp.zeros((), arr.dtype))

        if how == "inner":
            outs = tuple(wsel(pv, x[li]) for x in lps_r)
            outs += tuple(wsel(pv, x[ri]) for x in rps_r)
            return outs + (pv, lm, (o1 | o2 | o3)[None])

        # semi/anti: reduce pair hits onto left rows
        hit = (
            jnp.zeros(lh_r.shape, jnp.int32).at[li].add(pv.astype(jnp.int32), mode="drop") > 0
        )
        keep = (lm & hit) if how == "left_semi" else (lm & ~hit)
        return tuple(lps_r) + (keep, lm, (o1 | o2 | o3)[None])

    in_lanes = [l_present, r_present] + l_lanes + r_lanes
    n_out = (nl_lanes + nr_lanes if how == "inner" else nl_lanes) + 3
    spec = P(axis)
    f = cached_sm(
        ("join_table", mesh, axis, int(capacity), cap_out, how,
         tuple(l_kpos), tuple(r_kpos), nl_lanes, nr_lanes,
         tuple(str(a.dtype) for a in in_lanes)),
        lambda: jax.jit(shard_map(
            body, mesh=mesh, in_specs=(spec,) * len(in_lanes), out_specs=(spec,) * n_out
        )),
    )
    outs = f(*in_lanes)
    ovf = bool(np.asarray(outs[-1]).any())
    keep = np.asarray(outs[-3])
    sel = jnp.asarray(np.flatnonzero(keep))

    def rebuild(tbl: Table, metas, has_v, received, skip_keys: bool):
        cols, names = [], []
        it = iter(received)
        for name, meta, nullable in zip(tbl.names, metas, has_v):
            data = next(it)[sel]
            validity = next(it)[sel].astype(bool) if nullable else None
            if skip_keys and name in on:
                continue
            cols.append(_rebuild(meta, data, validity))
            names.append(name)
        return cols, names

    received = [jnp.asarray(o) for o in outs[: n_out - 3]]
    l_recv = received[:nl_lanes]
    cols, names = rebuild(left, l_metas, l_hasv, l_recv, skip_keys=False)
    if how == "inner":
        r_recv = received[nl_lanes:]
        rc, rn = rebuild(right, r_metas, r_hasv, r_recv, skip_keys=True)
        for c, nm in zip(rc, rn):
            names.append(nm if nm not in names else f"{nm}_right")
            cols.append(c)
    return Table(cols, names=names), ovf

"""Memoized jit(shard_map) executables for the distributed op tier.

Why this exists: an EAGER shard_map executes its body primitive-by-
primitive (one tiny XLA compile per op — ~100 s wall for the exact-f64
window graph on a 1-core box), so every site wraps its shard_map in
``jax.jit``. But jit's executable cache is keyed on the *callable
object*: a body closure rebuilt per call would retrace and recompile
the whole program every time. This module is the missing memo — the
jitted callable is cached on an explicit key of everything the body
closes over (mesh, axis, capacities, lane counts, agg descriptors);
jit then layers its own per-shape cache under each entry.

The key MUST capture every closed-over static. A missed key component
means two configs share one compiled program — jit re-traces on shape
changes, but a Python-level static (a capacity, an agg list) baked into
the first trace would silently serve the second config. Sites therefore
build keys from ALL their locals that feed the body.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

import jax

try:
    # modern spelling (jax >= 0.5); older jax ships it under
    # experimental with the same (f, mesh, in_specs, out_specs)
    # surface. Every distributed site imports the symbol from here so
    # the whole parallel tier degrades together, not call-site by
    # call-site.
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map

_CACHE: OrderedDict = OrderedDict()
_MAX_ENTRIES = 128


def cached_sm(key, build: Callable):
    """Return the memoized jitted shard_map for ``key``, building it
    with ``build()`` (-> jax.jit(jax.shard_map(...))) on first use."""
    f = _CACHE.get(key)
    if f is None:
        while len(_CACHE) >= _MAX_ENTRIES:
            _CACHE.popitem(last=False)
        f = _CACHE[key] = build()
    else:
        _CACHE.move_to_end(key)
    return f


def entry_count() -> int:
    return len(_CACHE)


def clear() -> int:
    """Drop every memoized executable; returns how many were dropped.
    Compiled programs hold device constants, so this frees real device
    memory at the cost of recompiling on next use — the memory
    governor's pressure loop (memgov/pressure.py) calls it as an
    opt-in last resort (SRJT_MEMGOV_DROP_SMCACHE=1)."""
    n = len(_CACHE)
    _CACHE.clear()
    return n

"""Distributed relational ops over the mesh: the 1M-row GROUP BY SUM
stepping stone (BASELINE.json configs[0]) end to end on ICI.

Pipeline (all one XLA program under shard_map — zero host round-trips
between stages):

1. hash each shard's local key rows -> destination shard (pmod),
2. all_to_all bucket exchange (parallel/shuffle framing),
3. static-capacity local groupby on each shard: sort received rows,
   segment-reduce into a fixed [capacity] accumulator (XLA-friendly
   replacement for a hash table),
4. tiny host-side compaction of the [n_shards, capacity] partials.

``shard_groupby_sum`` is the static-shape groupby usable inside
``shard_map`` (the jit-safe sibling of ops.aggregate.groupby_aggregate,
which host-syncs its group count).

Scope note (ISSUE 16): everything here is the IN-MESH tier — shards of
ONE runtime, one failure domain, XLA moving the bytes. The
cross-PROCESS N-rank tier lives in ``shuffle.TcpExchange`` +
``cluster.ClusterView``: membership, heartbeat liveness, and
epoch-fenced lineage recovery, where a rank can die mid-query and the
exchange fails over instead of erroring. A distributed groupby that
must survive member churn runs THERE (the plan compiler's Exchange
stage); this module's collective assumes every shard answers.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.hashing import murmur3_raw
from .shuffle import _bucketize
from ._smcache import cached_sm, shard_map

__all__ = ["shard_groupby_sum", "distributed_groupby_sum", "distributed_groupby_sum_multi"]


def _hash_dest_multi(key_arrays, n_parts: int) -> jnp.ndarray:
    """Chained murmur3 over raw key columns pmod n_parts (Spark
    Murmur3Hash chaining: each column hashes with the running hash as
    seed) — exact parity with hash_partition_map on the equivalent
    Columns, jit-safe inside shard_map."""
    h = None
    for k in key_arrays:
        h = murmur3_raw(k) if h is None else murmur3_raw(k, seed=h)
    signed = lax.bitcast_convert_type(h, jnp.int32)
    m = signed % jnp.int32(n_parts)
    return jnp.where(m < 0, m + n_parts, m)


def _hash_dest(keys: jnp.ndarray, n_parts: int) -> jnp.ndarray:
    """Single-key convenience over _hash_dest_multi."""
    return _hash_dest_multi([keys], n_parts)


def shard_groupby_sum(
    keys: jnp.ndarray,  # [n] int key lanes (one column, int32/int64)
    vals: jnp.ndarray,  # [n] numeric values
    present: jnp.ndarray,  # [n] bool occupancy (exchange padding mask)
    capacity: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Static-shape groupby-sum: returns (keys[capacity], sums[capacity],
    group_valid[capacity], overflow[]). Absent rows are excluded; group
    count beyond capacity flags overflow. Single-key convenience over
    _shard_groupby_sum_multi — ONE copy of the segmentation logic."""
    out_keys, sums, group_valid, overflow = _shard_groupby_sum_multi(
        [keys], vals, present, capacity
    )
    return out_keys[0], sums, group_valid, overflow


def distributed_groupby_sum(
    keys: jnp.ndarray,  # [N_global] int64/int32 keys, row-sharded
    vals: jnp.ndarray,  # [N_global] values, row-sharded
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    group_capacity: Optional[int] = None,
):
    """GROUP BY key SUM(val) across the mesh. Returns (keys, sums) as a
    host pair of np arrays (compacted), plus an overflow flag.

    One compiled program: pmod partition -> all_to_all -> per-shard
    sort+segment-reduce. capacity = per-destination bucket rows;
    group_capacity = max distinct keys per shard.
    """
    n_parts = mesh.shape[axis]
    n_global = keys.shape[0]
    per_shard = n_global // n_parts
    if capacity is None:
        capacity = per_shard
    if group_capacity is None:
        group_capacity = capacity * n_parts

    cap_g = int(group_capacity)

    def body(k, v):
        dest = _hash_dest(k, n_parts)
        kb, mask, ovf1 = _bucketize(k, dest, n_parts, capacity)
        vb, _, _ = _bucketize(v, dest, n_parts, capacity)
        kr = lax.all_to_all(kb, axis, split_axis=0, concat_axis=0, tiled=True)
        vr = lax.all_to_all(vb, axis, split_axis=0, concat_axis=0, tiled=True)
        mr = lax.all_to_all(mask, axis, split_axis=0, concat_axis=0, tiled=True)
        gk, gs, gv, ovf2 = shard_groupby_sum(
            kr.reshape(-1), vr.reshape(-1), mr.reshape(-1), cap_g
        )
        return gk[None], gs[None], gv[None], (ovf1 | ovf2)[None]

    f = cached_sm(
        ("gb_sum", mesh, axis, int(capacity), cap_g),
        lambda: jax.jit(shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=(P(axis), P(axis), P(axis), P(axis)),
        )),
    )
    gk, gs, gv, ovf = f(keys, vals)

    gk_h = np.asarray(gk).reshape(-1)
    gs_h = np.asarray(gs).reshape(-1)
    gv_h = np.asarray(gv).reshape(-1)
    keep = gv_h
    return gk_h[keep], gs_h[keep], bool(np.asarray(ovf).any())


def _shard_groupby_sum_multi(key_arrays, vals, present, capacity: int):
    """Multi-key sibling of shard_groupby_sum: lexsort over all key
    columns (occupancy primary), segment where ANY key changes."""
    order = jnp.lexsort(tuple(reversed(list(key_arrays))) + (~present,))
    ks = [k[order] for k in key_arrays]
    vs = jnp.where(present, vals, 0)[order]
    if jnp.issubdtype(vs.dtype, jnp.integer):
        vs = vs.astype(jnp.int64)
    ps = present[order]

    changed = jnp.zeros((ks[0].shape[0] - 1,), bool)
    for k in ks:
        changed = changed | (k[1:] != k[:-1])
    new_seg = jnp.concatenate([jnp.ones((1,), bool), changed]) & ps
    seg = jnp.cumsum(new_seg).astype(jnp.int32) - 1
    num_groups = jnp.maximum(seg[-1] + 1, 0)
    overflow = num_groups > capacity
    seg = jnp.where(ps, jnp.clip(seg, 0, capacity - 1), capacity)

    sums = jax.ops.segment_sum(vs, seg, num_segments=capacity + 1)[:capacity]
    out_keys = [
        jnp.zeros((capacity,), k.dtype).at[seg].set(kk, mode="drop")
        for k, kk in zip(key_arrays, ks)
    ]
    group_valid = jnp.arange(capacity, dtype=jnp.int32) < num_groups
    return out_keys, sums, group_valid, overflow


def distributed_groupby_sum_multi(
    key_arrays,  # sequence of [N_global] int arrays, row-sharded alike
    vals: jnp.ndarray,
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    group_capacity: Optional[int] = None,
):
    """GROUP BY (k1, k2, ...) SUM(val) across the mesh — the composite-
    key form of distributed_groupby_sum (Spark group-by keys are usually
    composite; rows of one key TUPLE land on one shard via chained
    murmur3). Returns (list of key arrays, sums, overflow)."""
    key_arrays = list(key_arrays)
    n_parts = mesh.shape[axis]
    n_global = key_arrays[0].shape[0]
    per_shard = n_global // n_parts
    if capacity is None:
        capacity = per_shard
    if group_capacity is None:
        group_capacity = capacity * n_parts
    cap_g = int(group_capacity)
    nk = len(key_arrays)

    def body(v, *ks):
        dest = _hash_dest_multi(ks, n_parts)
        bucketed = [_bucketize(k, dest, n_parts, capacity) for k in ks]
        vb, _, _ = _bucketize(v, dest, n_parts, capacity)
        _, mask, ovf1 = bucketed[0]
        a2a = lambda x: lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
        krs = [a2a(kb).reshape(-1) for kb, _, _ in bucketed]
        vr = a2a(vb).reshape(-1)
        mr = a2a(mask).reshape(-1)
        gks, gs, gv, ovf2 = _shard_groupby_sum_multi(krs, vr, mr, cap_g)
        out = tuple(gk[None] for gk in gks) + (gs[None], gv[None], (ovf1 | ovf2)[None])
        return out

    f = cached_sm(
        ("gb_sum_multi", mesh, axis, int(capacity), cap_g, nk),
        lambda: jax.jit(shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis),) * (nk + 1),
            out_specs=(P(axis),) * (nk + 3),
        )),
    )
    outs = f(vals, *key_arrays)
    gks, gs, gv, ovf = outs[:nk], outs[nk], outs[nk + 1], outs[nk + 2]
    keep = np.asarray(gv).reshape(-1)
    out_keys = [np.asarray(g).reshape(-1)[keep] for g in gks]
    return out_keys, np.asarray(gs).reshape(-1)[keep], bool(np.asarray(ovf).any())

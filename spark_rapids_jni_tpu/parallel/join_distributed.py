"""Distributed hash join over the mesh (the exchange-heavy TPC-DS q95
shape, BASELINE.json configs[3]).

Plan shape = Spark's shuffled hash join on the RAPIDS plugin: both
sides hash-partition by key onto the same shard (two all_to_all
exchanges over ICI), then each shard joins its buckets locally — all
one compiled program under ``shard_map``.

The local join is static-shape (XLA discipline): sort the received
right side by key, locate each left row's match run with two
searchsorted probes, expand runs into (left, right) index pairs bounded
by ``out_capacity`` with an occupancy mask; run overflow is *detected*
(flag) like the shuffle's bucket overflow.

Scope note (ISSUE 16): this is the in-mesh join — one runtime, one
failure domain. The cross-process N-rank equivalent is a plan-compiler
``Exchange`` stage on the join/group keys over ``TcpExchange`` with a
``cluster.ClusterView`` attached (membership + epoch-fenced recovery);
see ``plan/distribute.py``. Small build sides skip the exchange there
entirely: the shard catalog replicates them per rank (broadcast join),
so only the fact side's key space ever moves.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.dispatch import op_boundary
from .distributed import _hash_dest
from .shuffle import _bucketize
from ._smcache import cached_sm, shard_map

__all__ = ["shard_join_pairs", "distributed_inner_join"]


def shard_join_pairs(
    lk: jnp.ndarray,  # [nl] left keys
    lp: jnp.ndarray,  # [nl] left present mask
    rk: jnp.ndarray,  # [nr] right keys
    rp: jnp.ndarray,  # [nr] right present mask
    out_capacity: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Static-shape inner-join pair expansion.

    Returns (left_idx[out_capacity], right_idx[out_capacity],
    pair_valid[out_capacity], overflow[]). Indices refer to the input
    arrays; absent rows never match.
    """
    nr = rk.shape[0]
    # sort right by (absent-last, key); absent rows can't collide with
    # any real key because occupancy is the primary sort key
    rorder = jnp.lexsort((rk, ~rp))
    rks = rk[rorder]
    rps = rp[rorder]
    n_right_valid = jnp.sum(rps.astype(jnp.int32))

    # padding rows sit after the valid prefix but carry arbitrary key
    # values; give them the max key so the PROBE array stays monotone.
    # A real max-valued key's run can then extend into padding — the
    # clamp to n_right_valid below cuts it back to real rows only.
    rks_probe = jnp.where(rps, rks, jnp.iinfo(rks.dtype).max)

    # match runs, bounded to the valid prefix
    lo = jnp.searchsorted(rks_probe, lk, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(rks_probe, lk, side="right").astype(jnp.int32)
    lo = jnp.minimum(lo, n_right_valid)
    hi = jnp.minimum(hi, n_right_valid)
    cnt = jnp.where(lp, hi - lo, 0).astype(jnp.int64)  # int64: a skewed
    # shard can exceed 2^31 candidate pairs; int32 would wrap the scan
    # and silently defeat the overflow flag

    starts = jnp.cumsum(cnt) - cnt  # exclusive scan
    total = starts[-1] + cnt[-1] if cnt.shape[0] else jnp.zeros((), cnt.dtype)
    overflow = total > out_capacity

    j = jnp.arange(out_capacity, dtype=jnp.int64)
    # left row owning output slot j = first row whose cumulative END
    # exceeds j; empty runs (cnt 0) have end == start <= j and are
    # skipped by the 'right' search, so they never claim a slot
    ends = starts + cnt
    left_row = jnp.clip(
        jnp.searchsorted(ends, j, side="right"), 0, max(lk.shape[0] - 1, 0)
    ).astype(jnp.int32)
    within = j - starts[left_row]
    pair_valid = (j < total) & (within >= 0) & (within < cnt[left_row])
    right_sorted_idx = jnp.clip(lo[left_row] + within, 0, max(nr - 1, 0))
    right_row = rorder[right_sorted_idx].astype(jnp.int32)
    return left_row, right_row, pair_valid, overflow


@op_boundary("distributed_inner_join")
def distributed_inner_join(
    left_key: jnp.ndarray,  # [NL_global] row-sharded
    left_val: jnp.ndarray,  # [NL_global]
    right_key: jnp.ndarray,  # [NR_global] row-sharded
    right_val: jnp.ndarray,  # [NR_global]
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    out_capacity: Optional[int] = None,
):
    """Inner join on integer keys across the mesh; returns host arrays
    (lk, lv, rv) of matched rows plus an overflow flag.

    One program: pmod partition of both sides -> two all_to_alls ->
    per-shard sorted-run join. ``capacity`` bounds per-destination
    bucket rows; ``out_capacity`` bounds per-shard output pairs.
    """
    n_parts = mesh.shape[axis]
    per_l = left_key.shape[0] // n_parts
    per_r = right_key.shape[0] // n_parts
    if capacity is None:
        capacity = max(per_l, per_r)
    if out_capacity is None:
        out_capacity = capacity * n_parts * 2
    cap_out = int(out_capacity)

    def body(lk, lv, rk, rv):
        ld = _hash_dest(lk, n_parts)
        rd = _hash_dest(rk, n_parts)
        lkb, lmask, o1 = _bucketize(lk, ld, n_parts, capacity)
        lvb, _, _ = _bucketize(lv, ld, n_parts, capacity)
        rkb, rmask, o2 = _bucketize(rk, rd, n_parts, capacity)
        rvb, _, _ = _bucketize(rv, rd, n_parts, capacity)
        a2a = lambda x: lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
        lkr, lvr, lmr = a2a(lkb).reshape(-1), a2a(lvb).reshape(-1), a2a(lmask).reshape(-1)
        rkr, rvr, rmr = a2a(rkb).reshape(-1), a2a(rvb).reshape(-1), a2a(rmask).reshape(-1)

        li, ri, pv, o3 = shard_join_pairs(lkr, lmr, rkr, rmr, cap_out)
        out_k = jnp.where(pv, lkr[li], 0)
        out_lv = jnp.where(pv, lvr[li], 0)
        out_rv = jnp.where(pv, rvr[ri], 0)
        ovf = (o1 | o2 | o3)[None]
        return out_k[None], out_lv[None], out_rv[None], pv[None], ovf

    f = cached_sm(
        ("join_pairs", mesh, axis, int(capacity), cap_out),
        lambda: jax.jit(shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
        )),
    )
    k, lv, rv, pv, ovf = f(left_key, left_val, right_key, right_val)
    k_h = np.asarray(k).reshape(-1)
    lv_h = np.asarray(lv).reshape(-1)
    rv_h = np.asarray(rv).reshape(-1)
    pv_h = np.asarray(pv).reshape(-1)
    return k_h[pv_h], lv_h[pv_h], rv_h[pv_h], bool(np.asarray(ovf).any())

"""Executor <-> device binding: the ``auto_set_device`` analog.

The reference binds every JNI call to the executor's GPU via
``cudf::jni::auto_set_device(env)`` (RowConversionJni.cpp:29 et al,
SURVEY §2.9). The TPU analog: each Spark executor process owns one PJRT
device; ops dispatch under ``jax.default_device``. PTDS (per-thread
streams) maps onto XLA's async dispatch — each executor task thread
enqueues independently, the runtime orders by data dependence.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax

__all__ = ["device_for_executor", "bind_executor", "current_device"]

_local = threading.local()


def device_for_executor(executor_id: int):
    """Deterministic executor -> device mapping (round robin over local
    devices, as Spark maps executors to GPUs by ordinal)."""
    devs = jax.local_devices()
    return devs[executor_id % len(devs)]


@contextlib.contextmanager
def bind_executor(executor_id: int):
    """Scope ops to this executor's device; reentrant per thread."""
    dev = device_for_executor(executor_id)
    prev = getattr(_local, "device", None)
    _local.device = dev
    try:
        with jax.default_device(dev):
            yield dev
    finally:
        _local.device = prev


def current_device():
    dev = getattr(_local, "device", None)
    return dev if dev is not None else jax.local_devices()[0]

"""Device mesh construction for single-chip to multi-pod topologies.

The scaling recipe: pick a mesh, annotate shardings, let XLA insert the
collectives. Axis convention (outer -> inner):

- ``dcn``  : across pods/hosts (slow interconnect) — data parallel only
- ``data`` : across chips on ICI — Spark-partition parallelism, the axis
             the shuffle's all_to_all rides
- ``model``: optional intra-op axis (large joins/aggs can shard the
             build side across it)

``make_mesh`` with no arguments gives the whole-process default: all
devices on one ``data`` axis.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "row_sharding", "replicated", "shard_table_rows"]


def make_mesh(
    axes: Optional[Dict[str, int]] = None, devices: Optional[Sequence] = None
) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if axes is None:
        axes = {"data": len(devs)}
    names = tuple(axes.keys())
    shape = tuple(axes.values())
    total = int(np.prod(shape))
    if total != len(devs):
        raise ValueError(f"mesh axes {axes} need {total} devices, have {len(devs)}")
    return Mesh(np.asarray(devs).reshape(shape), names)


def row_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Rows split along `axis`, other dims replicated."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_table_rows(table, mesh: Mesh, axis: str = "data"):
    """Place each column's buffers row-sharded over the mesh axis.

    Rows must divide the axis size (pad upstream); string columns keep
    offsets/chars replicated (exchange of ragged payloads happens via
    the dictionary/byte-matrix paths).
    """
    from ..columnar import Column, Table

    sh = row_sharding(mesh, axis)
    cols = []
    for c in table.columns:
        if c.data is not None:
            data = jax.device_put(c.data, sh)
            validity = None if c.validity is None else jax.device_put(c.validity, sh)
            cols.append(Column(c.dtype, data=data, validity=validity))
        else:
            cols.append(c)
    return Table(cols, table.names)

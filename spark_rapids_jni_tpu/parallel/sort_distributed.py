"""Distributed sort over the mesh: range-partitioned sample sort.

The global ORDER BY tier (Spark's rangepartitioning exchange + local
sort, which the RAPIDS plugin runs as a sample-sort over its shuffle).
One compiled program under ``shard_map``:

1. sort the local shard,
2. sample `oversample` evenly-spaced keys per shard, all_gather them
   over ICI (tiny collective), sort, take P-1 splitters,
3. route each row by splitter range (searchsorted — rows of shard i are
   all <= rows of shard i+1), static-capacity bucket all_to_all,
4. sort the received rows (absent-last), leaving each shard a sorted
   run; shard order == global order.

Operates on raw INTEGER key arrays (the shard_map calling convention).
FLOAT64 callers must pre-transform bits with
``ops.bitutils.total_order_key`` (monotone, invertible) — raw f64 bit
patterns do NOT sort numerically. Capacity overflow is detected like
the shuffle's.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.dispatch import op_boundary
from .shuffle import _bucketize
from ._smcache import cached_sm, shard_map

__all__ = ["distributed_sort"]


@op_boundary("distributed_sort")
def distributed_sort(
    keys: jnp.ndarray,  # [N_global] integer keys, row-sharded
    mesh: Mesh,
    axis: str = "data",
    capacity: Optional[int] = None,
    oversample: int = 32,
    descending: bool = False,
):
    """Globally sort row-sharded keys. Returns (sorted_host, overflow):
    the fully sorted host array (for the caller's gather/limit step) and
    the capacity-overflow flag. Device-side, each shard ends holding a
    sorted run with shard order == global order (the useful invariant
    for downstream merge/limit operators)."""
    n_parts = mesh.shape[axis]
    n_global = keys.shape[0]
    per_shard = n_global // n_parts
    if capacity is None:
        # tight: a source shard holds only per_shard rows, so no
        # (src, dst) bucket can exceed that regardless of skew
        capacity = per_shard
    samples_per = min(oversample, per_shard)

    def body(k):
        ks = jnp.sort(k)
        # evenly spaced local sample (positions cover the whole run)
        pos = (jnp.arange(samples_per) * k.shape[0]) // samples_per
        local_samples = ks[pos]
        all_samples = lax.all_gather(local_samples, axis).reshape(-1)
        all_sorted = jnp.sort(all_samples)
        # P-1 splitters at even ranks
        spl_pos = (jnp.arange(1, n_parts) * all_sorted.shape[0]) // n_parts
        splitters = all_sorted[spl_pos]
        dest = jnp.searchsorted(splitters, k, side="right").astype(jnp.int32)

        kb, mask, ovf = _bucketize(k, dest, n_parts, capacity)
        kr = lax.all_to_all(kb, axis, split_axis=0, concat_axis=0, tiled=True)
        mr = lax.all_to_all(mask, axis, split_axis=0, concat_axis=0, tiled=True)
        kf, mf = kr.reshape(-1), mr.reshape(-1)
        # sort received with absent rows last (occupancy-primary sort)
        order = jnp.lexsort((kf, ~mf))
        return kf[order][None], mf[order][None], ovf[None]

    f = cached_sm(
        ("sample_sort", mesh, axis, int(capacity), int(samples_per)),
        lambda: jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(axis),), out_specs=(P(axis), P(axis), P(axis))
        )),
    )
    vals, mask, ovf = f(keys)

    v = np.asarray(vals).reshape(n_parts, -1)
    m = np.asarray(mask).reshape(n_parts, -1)
    out = np.concatenate([v[i][m[i]] for i in range(n_parts)])
    if descending:
        out = out[::-1]
    return out, bool(np.asarray(ovf).any())

"""Cluster membership, liveness, and epoch-fenced recovery (ISSUE 16).

The pool's supervision discipline lifted from workers to hosts: a
``ClusterView`` owns the rank→address map for an N-rank TCP exchange
fabric, probes every peer with ``TcpExchange.ping`` heartbeats, scores
round-trip health with the same EWMA discipline ``sidecar_pool`` uses
for workers, and walks each peer through ``ALIVE → SUSPECT → DEAD``
on consecutive misses. Death is a *membership event*, not just a
local observation: it bumps the cluster **generation**, which the
exchange stamps into every fenced publish/fetch — so bytes from a
rank still serving a pre-death world view are refused undecoded
(``_EXC_STALE``) and surface to the puller as a retryable desync
rather than wrong rows. That fencing contract is what makes recovery
safe to run concurrently with in-flight pulls.

Recovery itself is lineage-based, Spark-style: the attached
``lineage(rank)`` callback reproduces a dead rank's *input* shard
deterministically (the demo harness re-slices the seeded table; the
plan compiler replays the dead rank's child subtree over its shard of
the catalog). ``recover_partition`` re-partitions that input and
republishes the dead rank's outgoing partitions under a derived
recovery epoch (``epoch + (dead_rank+1) * _RECOVERY_EPOCH_STRIDE``) at
the bumped generation; ``failover_fetch`` is the pull-side entry the
exchange's all-to-all uses once a peer's retry budget is spent. The
destination-side hole (partitions that were headed *to* the dead
rank) is the coordinator's to reassign — ``recompute_dead_partition``
rebuilds exactly that partition from every rank's lineage.

State machine (see README "Cluster" for the operator view)::

    ALIVE --misses >= SRJT_CLUSTER_SUSPECT_MISSES--> SUSPECT
    SUSPECT --misses >= SRJT_CLUSTER_DEAD_MISSES--> DEAD (generation += 1)
    SUSPECT --one successful ping--> ALIVE (misses reset)
    DEAD is terminal for the generation; a replacement rank joins as a
    new address under the bumped generation, never as a resurrection.

Thread model: one daemon heartbeat thread per view; all state behind
one lock + condition (``await_dead`` waiters are notified on every
transition). Heartbeat cadence/timeout/thresholds/quorum all come
from ``SRJT_CLUSTER_*`` knobs (utils/knobs.py) so chaos profiles and
deployments tune them without code edits.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..columnar import Table
from ..utils import knobs, metrics, tracing
from ..utils.errors import FatalDeviceError

__all__ = ["ALIVE", "SUSPECT", "DEAD", "ClusterView"]

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"


class ClusterView:
    """Membership + liveness + recovery coordinator for one rank of an
    N-rank exchange fabric. ``addresses`` maps every rank (including
    ``rank`` itself) to ``host:port``. Construction installs
    generation 1 into the exchange — from that point every fenced
    publish/fetch carries it. ``start()`` launches the heartbeat
    thread; a view used purely for fencing/bookkeeping (e.g. a test
    driving transitions by hand via ``mark_dead``) may skip it."""

    def __init__(self, rank: int, addresses: Dict[int, str],
                 exchange, *,
                 lineage: Optional[Callable[[int], Table]] = None,
                 on_transition: Optional[Callable[[int, str, str], None]] = None,
                 heartbeat_s: Optional[float] = None,
                 heartbeat_timeout_s: Optional[float] = None,
                 suspect_misses: Optional[int] = None,
                 dead_misses: Optional[int] = None,
                 quorum_fraction: Optional[float] = None) -> None:
        if rank not in addresses:
            raise ValueError(
                f"cluster addresses must include this rank {rank} "
                f"(got ranks {sorted(addresses)})"
            )
        self.rank = int(rank)
        self.addresses = dict(addresses)
        self.world = len(self.addresses)
        self._exchange = exchange
        self._lineage = lineage
        self._on_transition = on_transition
        self.heartbeat_s = (
            knobs.get_float("SRJT_CLUSTER_HEARTBEAT_SEC")
            if heartbeat_s is None else float(heartbeat_s)
        )
        self.heartbeat_timeout_s = (
            knobs.get_float("SRJT_CLUSTER_HEARTBEAT_TIMEOUT_SEC")
            if heartbeat_timeout_s is None else float(heartbeat_timeout_s)
        )
        self.suspect_misses = (
            knobs.get_int("SRJT_CLUSTER_SUSPECT_MISSES")
            if suspect_misses is None else int(suspect_misses)
        )
        self.dead_misses = (
            knobs.get_int("SRJT_CLUSTER_DEAD_MISSES")
            if dead_misses is None else int(dead_misses)
        )
        self.quorum_fraction = (
            knobs.get_float("SRJT_CLUSTER_QUORUM_FRACTION")
            if quorum_fraction is None else float(quorum_fraction)
        )
        if self.dead_misses < self.suspect_misses:
            raise ValueError(
                f"SRJT_CLUSTER_DEAD_MISSES ({self.dead_misses}) must be >= "
                f"SRJT_CLUSTER_SUSPECT_MISSES ({self.suspect_misses})"
            )
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._generation = 1
        self._state: Dict[int, str] = {
            r: ALIVE for r in self.addresses if r != self.rank
        }
        self._misses: Dict[int, int] = {r: 0 for r in self._state}
        # EWMA heartbeat RTTs — the sidecar_pool health-scoring
        # discipline applied to hosts; jitter feeds operator stats,
        # not the miss thresholds (liveness must stay a hard count)
        self._rtt = metrics.KeyedEwma(alpha=0.3)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._recovered_epochs: set = set()
        exchange.set_generation(self._generation)
        metrics.registry().gauge("cluster.generation").set(self._generation)
        metrics.registry().gauge("cluster.alive").set(self.world)

    # -- membership readers -------------------------------------------------

    def generation(self) -> int:
        with self._lock:
            return self._generation

    def state(self, rank: int) -> str:
        with self._lock:
            if rank == self.rank:
                return ALIVE
            return self._state[rank]

    def alive_ranks(self) -> List[int]:
        with self._lock:
            alive = [r for r, s in self._state.items() if s != DEAD]
            return sorted(alive + [self.rank])

    def dead_ranks(self) -> List[int]:
        with self._lock:
            return sorted(r for r, s in self._state.items() if s == DEAD)

    def has_quorum(self) -> bool:
        """True while strictly more than ``quorum_fraction`` of the
        world is not DEAD — the serve layer sheds
        ``Overloaded(cause="cluster_degraded")`` when this goes
        false."""
        return len(self.alive_ranks()) > self.quorum_fraction * self.world

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "rank": self.rank,
                "world": self.world,
                "generation": self._generation,
                "states": dict(self._state),
                "rtt_ms": {
                    r: self._rtt.get(str(r)) for r in self._state
                },
            }

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            raise FatalDeviceError("ClusterView.start called twice")
        self._thread = threading.Thread(
            target=self._heartbeat_loop, name=f"cluster-hb-r{self.rank}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.heartbeat_timeout_s + self.heartbeat_s + 1.0)
        self._thread = None

    def set_lineage(self, fn: Callable[[int], Table]) -> None:
        """Install the deterministic input reproducer: ``fn(rank)``
        returns the table that rank fed into the exchange. Recovery is
        impossible without it — ``failover_fetch`` answers None and
        the pull keeps its original error."""
        with self._lock:
            self._lineage = fn

    # -- heartbeat engine ---------------------------------------------------

    def _heartbeat_loop(self) -> None:
        # Event.wait(heartbeat_s) is the cadence gate: interruptible
        # at stop(), bounded per iteration, never a bare sleep.
        while not self._stop.wait(self.heartbeat_s):
            for r, addr in self.addresses.items():
                if r == self.rank or self._stop.is_set():
                    continue
                with self._lock:
                    if self._state[r] == DEAD:
                        continue
                self._probe(r, addr)

    def _probe(self, r: int, addr: str) -> None:
        t0 = time.monotonic()
        try:
            peer_gen = self._exchange.ping(addr, self.heartbeat_timeout_s)
        except Exception as e:  # srjt-lint: allow-broad-except(heartbeat probe: ANY ping failure is one miss — classification happens at the miss-count threshold, not per-exception)
            self._record_miss(r, e)
            return
        rtt_ms = (time.monotonic() - t0) * 1e3
        with self._lock:
            self._rtt.update(str(r), rtt_ms)
        self._record_hit(r, peer_gen)

    def _record_hit(self, r: int, peer_gen: int) -> None:
        with self._lock:
            self._misses[r] = 0
            if self._state[r] == SUSPECT:
                self._transition_locked(r, SUSPECT, ALIVE)
            # adopt a higher generation seen on the wire: a peer that
            # already observed a death is ahead of us, and publishing
            # under our stale generation would get our bytes refused
            if peer_gen > self._generation:
                self._bump_generation_locked(peer_gen)

    def _record_miss(self, r: int, exc: BaseException) -> None:
        with self._lock:
            if self._state[r] == DEAD:
                return
            self._misses[r] += 1
            n = self._misses[r]
            if self._state[r] == ALIVE and n >= self.suspect_misses:
                self._transition_locked(r, ALIVE, SUSPECT, reason=repr(exc))
            if self._state[r] == SUSPECT and n >= self.dead_misses:
                self._declare_dead_locked(r, reason=repr(exc))

    def _transition_locked(self, r: int, old: str, new: str,
                           reason: str = "") -> None:
        self._state[r] = new
        metrics.registry().counter("cluster.transitions").inc()
        metrics.event(
            "cluster.transition", rank=r, old=old, new=new,
            generation=self._generation, observer=self.rank, reason=reason,
        )
        cb = self._on_transition
        self._cond.notify_all()
        if cb is not None:
            cb(r, old, new)

    def _declare_dead_locked(self, r: int, reason: str = "") -> None:
        self._transition_locked(r, self._state[r], DEAD, reason=reason)
        metrics.registry().counter("cluster.deaths").inc()
        dead = sum(1 for s in self._state.values() if s == DEAD)
        metrics.registry().gauge("cluster.alive").set(self.world - dead)
        # generation is a FUNCTION of membership (1 + deaths known),
        # not a per-observer counter: every view that learns of the
        # same death — locally or by wire adoption — lands on the same
        # number, so independent observers cannot compound one death
        # into divergent generations
        target = 1 + dead
        if target > self._generation:
            self._bump_generation_locked(target)

    def _bump_generation_locked(self, new_gen: int) -> None:
        self._generation = int(new_gen)
        self._exchange.set_generation(self._generation)
        metrics.registry().gauge("cluster.generation").set(self._generation)
        self._cond.notify_all()

    # -- test / coordinator hooks -------------------------------------------

    def mark_dead(self, r: int) -> None:
        """Force a rank DEAD (coordinator observed the death out of
        band — e.g. the supervisor reaped the process). Same
        transition path as the heartbeat detector: generation bumps,
        fencing engages, waiters wake."""
        with self._lock:
            if self._state[r] == DEAD:
                return
            if self._state[r] == ALIVE:
                self._transition_locked(r, ALIVE, SUSPECT,
                                        reason="marked dead out of band")
            self._declare_dead_locked(r, reason="marked dead out of band")

    def await_dead(self, r: int, timeout_s: float) -> bool:
        """Block until ``r`` is DEAD or the deadline passes; returns
        whether it died. The failover path's rendezvous with the
        heartbeat detector."""
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        with self._cond:
            while self._state[r] != DEAD:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    # -- recovery -----------------------------------------------------------

    def failover_grace_s(self) -> float:
        """How long a failed pull waits for the detector to confirm
        death before giving up on failover: the full miss ladder plus
        one probe timeout plus slack."""
        return (self.dead_misses * self.heartbeat_s
                + self.heartbeat_timeout_s + 1.0)

    def failover_fetch(self, dead_rank: int, epoch: int,
                       key_cols: List[str], world: int,
                       dest: int) -> Optional[Table]:
        """Pull-side recovery entry (called by the exchange after a
        peer's retry budget is spent): if the membership layer
        confirms ``dead_rank`` DEAD within the failover grace and a
        lineage is installed, recompute the dead rank's partitions and
        return the one headed for ``dest``. None means "not actually
        dead (or unrecoverable)" — the caller re-raises its original
        error."""
        if not self.await_dead(dead_rank, self.failover_grace_s()):
            return None
        with self._lock:
            lineage = self._lineage
        if lineage is None:
            return None
        return self.recover_partition(dead_rank, epoch, key_cols, world, dest)

    def recover_partition(self, dead_rank: int, epoch: int,
                          key_cols: List[str], world: int,
                          dest: int) -> Table:
        """Recompute ``dead_rank``'s exchange input from lineage,
        re-partition it, republish its outgoing partitions under the
        bumped generation at the derived recovery epoch, and return
        the partition headed for ``dest``. Republishing makes the
        recomputed copies fetchable by every OTHER surviving rank
        (single-hop: any survivor can serve them), idempotently — the
        first recovering rank on this view does the publish, later
        calls reuse it."""
        from .shuffle import _RECOVERY_EPOCH_STRIDE, hash_partition
        from ..ops.copying import slice_table

        with self._lock:
            lineage = self._lineage
        if lineage is None:
            raise FatalDeviceError(
                f"cluster recovery for rank {dead_rank} has no lineage"
            )
        recovery_epoch = (
            int(epoch) + (dead_rank + 1) * _RECOVERY_EPOCH_STRIDE
        )
        with tracing.span("cluster.recover_partition", dead_rank=dead_rank,
                          epoch=epoch, dest=dest):
            src = lineage(dead_rank)
            partitioned, offsets = hash_partition(src, world, key_cols)
            bounds = list(offsets) + [partitioned.num_rows]
            parts = {
                p: slice_table(partitioned, bounds[p], bounds[p + 1])
                for p in range(world)
            }
            with self._lock:
                first = (dead_rank, int(epoch)) not in self._recovered_epochs
                self._recovered_epochs.add((dead_rank, int(epoch)))
            if first:
                self._exchange.publish(
                    recovery_epoch,
                    {p: t for p, t in parts.items() if p != dead_rank},
                )
                metrics.registry().counter("cluster.recoveries").inc()
                metrics.event(
                    "cluster.recovery", dead_rank=dead_rank, epoch=epoch,
                    recovery_epoch=recovery_epoch,
                    generation=self.generation(), by=self.rank,
                )
        return parts[dest]

    def recompute_dead_partition(self, dead_rank: int,
                                 key_cols: List[str],
                                 world: int) -> Table:
        """The destination-side hole: rebuild the partition that was
        headed TO the dead rank (its share of every surviving rank's
        rows AND of its own lineage) so a coordinator can finish the
        dead rank's portion of the query. Pure lineage replay — no
        network."""
        from .shuffle import hash_partition
        from ..ops.copying import concatenate, slice_table

        with self._lock:
            lineage = self._lineage
        if lineage is None:
            raise FatalDeviceError(
                f"cluster recompute for rank {dead_rank} has no lineage"
            )
        full = concatenate([lineage(r) for r in range(world)])
        partitioned, offsets = hash_partition(full, world, key_cols)
        bounds = list(offsets) + [partitioned.num_rows]
        return slice_table(partitioned, bounds[dead_rank],
                           bounds[dead_rank + 1])

"""Benchmark: the BASELINE.json stepping-stone config[0] — single-table
GROUP BY SUM over 1M rows — on the live device, compared against the
config's stated reference ("CPU ColumnarBatch ref"): a numpy columnar
groupby on this host.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Observability (ISSUE 2): with ``SRJT_METRICS_ENABLED=1`` the BENCH row
is followed by one ``{"metrics": {...}}`` JSON line PER STAGE
(device_groupby, cpu_ref) — the utils/metrics stage report: op
timings, shuffle movement, retry counts, and memory splits, each stage
measured from a reset registry so the numbers are attributable. This
is how a BENCH row and its runtime counters land in the same artifact
(the BASELINE.json protocol's measured-evidence requirement).

Measurement protocol: the remote (axon) backend carries a large fixed
RPC latency per host sync, so the kernel is timed as a CHAINED
on-device loop (each iteration's keys depend on the previous sums, so
XLA cannot parallelize or elide them) at two loop lengths; the
difference isolates per-iteration device time with the round-trip
latency cancelled. Deterministic seeded input, compile excluded, median
of repeated measurements (nvbench discipline, SURVEY.md §6).
"""

from __future__ import annotations

import json
import time
from functools import partial

import numpy as np

import spark_rapids_jni_tpu  # noqa: F401  (enables x64 BEFORE arrays exist)
from spark_rapids_jni_tpu.ops.aggregate import groupby_sum_bounded

import jax
import jax.numpy as jnp
from jax import lax

N_ROWS = 1 << 20  # 1M-row stepping stone
N_KEYS = 4096  # distinct groups
REPS = 7
# 1024 chained iterations ~= 72ms of device time at the current kernel
# speed (~0.07 ms/iter after the transposed-layout MXU rewrite): the
# long-short difference must dwarf the axon tunnel's +-5ms run-to-run
# jitter or the derived per-iter is noise (round-2 regression:
# K_LONG=17 left a 2.5ms signal inside that jitter; the round-3 kernel
# made 257 marginal again)
K_SHORT, K_LONG = 1, 1025


@partial(jax.jit, static_argnums=(3, 4))
def _chained_groupby(keys, vals, present, num_keys: int, iters: int):
    del present  # bounded-domain path: occupancy handled by the domain

    def body(_, carry):
        k, acc = carry
        sums, counts = groupby_sum_bounded(k, vals, num_keys)
        # data dependency: next iteration's keys depend on these sums,
        # so the chain cannot be overlapped or dead-code-eliminated
        perturb = (sums[0] == 0.0).astype(k.dtype)
        return k ^ perturb, acc + sums[0]

    _, acc = lax.fori_loop(0, iters, body, (keys, jnp.float32(0)))
    return acc


def _timed_all(fn) -> "list[float]":
    out = fn()  # warmup/compile
    float(np.asarray(out))
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(np.asarray(fn()))  # host sync: full completion
        times.append(time.perf_counter() - t0)
    return times


def bench_device():
    rng = np.random.default_rng(42)
    keys = jnp.asarray(rng.integers(0, N_KEYS, N_ROWS), jnp.int64)
    vals = jnp.asarray(rng.standard_normal(N_ROWS), jnp.float32)
    present = jnp.ones((N_ROWS,), bool)
    cap = N_KEYS

    shorts = _timed_all(lambda: _chained_groupby(keys, vals, present, cap, K_SHORT))
    longs = _timed_all(lambda: _chained_groupby(keys, vals, present, cap, K_LONG))
    t_short = float(np.median(shorts))
    # per-rep per-iter spread (vs the short median): min/median/max so a
    # lucky run can't masquerade as the result (VERDICT r2 protocol)
    per_iters = sorted(max((tl - t_short) / (K_LONG - K_SHORT), 1e-9) for tl in longs)
    per_iter = per_iters[len(per_iters) // 2]
    return per_iter, per_iters, t_short, float(np.median(longs))


def bench_cpu_ref() -> float:
    """CPU ColumnarBatch reference: numpy bincount groupby (the fastest
    plain-columnar host implementation, favoring the baseline)."""
    rng = np.random.default_rng(42)
    keys_h = rng.integers(0, N_KEYS, N_ROWS).astype(np.int64)
    vals_h = rng.standard_normal(N_ROWS).astype(np.float32)

    np.bincount(keys_h, weights=vals_h, minlength=N_KEYS)  # warmup
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        np.bincount(keys_h, weights=vals_h, minlength=N_KEYS)
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


# --ooc mode (srjt-ooc, ISSUE 18): TPC-H q1's shape at a row count
# where compute dominates the strategy's fixed overhead, run in-core
# (unconstrained) and out-of-core (budget pinched to est/4, K=4
# spill-backed partitions). The BENCH row is the degradation price:
# ooc_overhead = OOC wall / in-core wall; ci/premerge.sh gates <= 2x.
# 1M rows: the exact-f64 aggregate path carries a per-invocation fixed
# cost the K passes each pay — smaller datasets measure that fixed
# cost x K, not the strategy (200k rows reads ~2.5x; 1M reads ~1.4x
# with the linear term dominant).
OOC_ROWS = 1_000_000
OOC_PARTS = 4
OOC_REPS = 3


def bench_ooc():
    import os

    from spark_rapids_jni_tpu import memgov
    from spark_rapids_jni_tpu import plan as P
    from spark_rapids_jni_tpu.models.tpch import gen_lineitem

    lineitem = gen_lineitem(OOC_ROWS, seed=11)
    tables = {"lineitem": lineitem}
    ir = P.Sort(
        P.Aggregate(
            P.Filter(P.Scan("lineitem"),
                     P.pcol("l_quantity") >= P.plit(0.0)),
            keys=("l_returnflag", "l_linestatus"),
            aggs=(P.AggSpec("l_quantity", "sum", "sum_qty"),
                  P.AggSpec("l_extendedprice", "sum", "sum_price"),
                  P.AggSpec(None, "count_all", "count_order")),
        ),
        keys=(("l_returnflag", True), ("l_linestatus", True)),
    )

    def med_wall(fn):
        fn()  # warmup: XLA compiles excluded, as everywhere in this file
        times = []
        for _ in range(OOC_REPS):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return float(np.median(times))

    cp_in = P.compile_ir(ir, tables, name="ooc_bench_incore")
    t_in = med_wall(cp_in)
    want = [np.asarray(c.data).tobytes() for c in cp_in().columns]

    est = cp_in.estimated_memory_bytes
    os.environ["SRJT_OOC_ENABLED"] = "1"  # srjt-lint: allow-environ(bench process owns its env; knobs read live)
    os.environ["SRJT_OOC_PARTITIONS"] = str(OOC_PARTS)  # srjt-lint: allow-environ(bench process owns its env)
    os.environ["SRJT_DEVICE_MEMORY_BUDGET"] = str(max(1, est // 4))  # srjt-lint: allow-environ(bench process owns its env)
    with memgov.enabled():
        cp_ooc = P.compile_ir(ir, tables, name="ooc_bench")
        assert isinstance(cp_ooc, P.OutOfCorePlan), \
            "budget est/4 did not select out-of-core"
        t_ooc = med_wall(cp_ooc)
        got = [np.asarray(c.data).tobytes() for c in cp_ooc().columns]
    assert got == want, "ooc bench diverged from the in-core answer"
    return t_in, t_ooc, est


def main_ooc():
    t_in, t_ooc, est = bench_ooc()
    print(json.dumps({
        "metric": "ooc_overhead",
        "value": round(t_ooc / t_in, 3),
        "unit": "x",
        # the gate ci/premerge.sh enforces on this row (kept in the
        # artifact so the number and its bar travel together)
        "gate_max": 2.0,
        "raw": {
            "rows": OOC_ROWS,
            "partitions": OOC_PARTS,
            "est_peak_bytes": est,
            "in_core_s": round(t_in, 5),
            "out_of_core_s": round(t_ooc, 5),
            "bit_identical": True,
        },
    }))


def main():
    from spark_rapids_jni_tpu.utils import metrics, retry, trace_sink, tracing

    emit_metrics = metrics.is_enabled()
    stage_snaps = []
    trace_snaps = []

    def staged(name, fn):
        """Run one bench stage with an attributable metrics window:
        registry + retry stats reset at entry, stage report captured at
        exit (timed through the op metrics namespace). With srjt-trace
        armed too (ISSUE 12), a per-stage trace summary — span count,
        max tree depth, p99 span duration — is captured from the same
        reset registry window, so a BENCH latency regression can be
        correlated with the span that grew. The trace summary rides
        the TRACING gate alone (its counters are registry-direct), so
        SRJT_TRACE_ENABLED=1 without SRJT_METRICS_ENABLED still emits
        it."""
        emit_trace = tracing.is_enabled()
        if not emit_metrics and not emit_trace:
            return fn()
        metrics.reset()
        retry.reset_stats()
        with metrics.timer(f"bench.{name}"):
            out = fn()
        if emit_metrics:
            stage_snaps.append(metrics.stage_report(name))
        if emit_trace:
            trace_snaps.append({"stage": name, **trace_sink.stage_summary()})
        return out

    t_dev, per_iters, t_short, t_long = staged("device_groupby", bench_device)
    t_cpu = staged("cpu_ref", bench_cpu_ref)
    mrows_s = (N_ROWS / t_dev) / 1e6
    vs_baseline = t_cpu / t_dev  # >1 means faster than the CPU ref
    print(
        json.dumps(
            {
                "metric": "groupby_sum_1M_rows",
                "value": round(mrows_s, 2),
                "unit": "Mrows/s",
                "vs_baseline": round(vs_baseline, 3),
                # raw protocol inputs so the derived per-iter can be
                # audited against tunnel-latency drift: per_iter =
                # (t_long - t_short) / (K_LONG - K_SHORT), and the
                # per-rep per-iter spread [best, median, worst] keeps a
                # lucky run from masquerading as the result
                "raw": {
                    "t_short_s": round(t_short, 5),
                    "t_long_s": round(t_long, 5),
                    "k_short": K_SHORT,
                    "k_long": K_LONG,
                    "cpu_ref_s": round(t_cpu, 5),
                    "per_iter_ms_min_med_max": [
                        round(per_iters[0] * 1e3, 4),
                        round(t_dev * 1e3, 4),
                        round(per_iters[-1] * 1e3, 4),
                    ],
                    "vs_baseline_worst": round(t_cpu / per_iters[-1], 3),
                },
            }
        )
    )
    # per-stage metrics snapshots ride NEXT TO the BENCH row, one JSON
    # line each, so the harness that archives the row archives the
    # runtime counters with it; armed tracing adds one {"trace": ...}
    # summary line per stage beside them
    for snap in stage_snaps:
        print(json.dumps({"metrics": snap}))
    for snap in trace_snaps:
        print(json.dumps({"trace": snap}))


if __name__ == "__main__":
    import sys

    if "--ooc" in sys.argv[1:]:
        main_ooc()
    else:
        main()

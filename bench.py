"""Benchmark: the BASELINE.json stepping-stone config[0] — single-table
GROUP BY SUM over 1M rows — on the live device (TPU chip under the
driver; CPU if forced), compared against the config's stated reference
("CPU ColumnarBatch ref"): a numpy columnar groupby on this host.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol mirrors the reference's nvbench discipline (SURVEY.md §6):
deterministic seeded input, warmup compile excluded, steady-state
median over repeated timed runs, rows/s reported.
"""

from __future__ import annotations

import json
import time

import numpy as np

import jax
import jax.numpy as jnp

N_ROWS = 1 << 20  # 1M-row stepping stone
N_KEYS = 4096  # distinct groups
REPS = 20


def _device_groupby(keys, vals, present, capacity):
    from spark_rapids_jni_tpu.parallel.distributed import shard_groupby_sum

    return shard_groupby_sum(keys, vals, present, capacity)


def bench_device() -> float:
    rng = np.random.default_rng(42)
    keys_h = rng.integers(0, N_KEYS, N_ROWS).astype(np.int64)
    vals_h = rng.standard_normal(N_ROWS).astype(np.float32)

    keys = jnp.asarray(keys_h)
    vals = jnp.asarray(vals_h)
    present = jnp.ones((N_ROWS,), bool)

    fn = jax.jit(_device_groupby, static_argnums=(3,))
    out = fn(keys, vals, present, N_KEYS * 2)  # warmup/compile
    jax.block_until_ready(out)

    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        out = fn(keys, vals, present, N_KEYS * 2)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def bench_cpu_ref() -> float:
    """CPU ColumnarBatch reference: numpy bincount groupby (the fastest
    plain-columnar host implementation, favoring the baseline)."""
    rng = np.random.default_rng(42)
    keys_h = rng.integers(0, N_KEYS, N_ROWS).astype(np.int64)
    vals_h = rng.standard_normal(N_ROWS).astype(np.float32)

    np.bincount(keys_h, weights=vals_h, minlength=N_KEYS)  # warmup
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        np.bincount(keys_h, weights=vals_h, minlength=N_KEYS)
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main():
    t_dev = bench_device()
    t_cpu = bench_cpu_ref()
    mrows_s = (N_ROWS / t_dev) / 1e6
    vs_baseline = t_cpu / t_dev  # >1 means faster than the CPU ref
    print(
        json.dumps(
            {
                "metric": "groupby_sum_1M_rows",
                "value": round(mrows_s, 2),
                "unit": "Mrows/s",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
